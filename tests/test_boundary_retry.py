"""CI twin of ``scripts/check_boundary_retry.py``: the controller's
``monitor()``/``apply_move()`` calls all route through the retry +
circuit-breaker boundary, never the raw backend."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_boundary_retry.py"
    )
    spec = importlib.util.spec_from_file_location("check_boundary_retry", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_boundary_retry", mod)
    spec.loader.exec_module(mod)
    return mod


def test_controller_has_no_raw_boundary_calls():
    checker = _load_checker()
    assert checker.violations() == []


def test_checker_catches_a_raw_call(tmp_path):
    checker = _load_checker()
    f = tmp_path / "mod.py"
    f.write_text(
        "def run(backend, boundary):\n"
        "    state = backend.monitor()\n"       # raw: flagged
        "    ok = boundary.monitor()\n"          # routed: allowed
        "    backend.apply_move(None)\n"         # raw: flagged
        "    backend.comm_graph()\n"             # not a boundary call
    )
    lines = [line for line, _ in checker.find_raw_boundary_calls(f)]
    assert lines == [2, 4]
