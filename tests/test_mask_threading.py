"""CI twin of ``scripts/check_mask_threading.py``: every solver/
attribution kernel entry point accepts and (transitively) reads the
validity masks, so padded bucket slots are provably inert."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_mask_threading.py"
    )
    spec = importlib.util.spec_from_file_location("check_mask_threading", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_mask_threading", mod)
    spec.loader.exec_module(mod)
    return mod


def test_repo_kernels_all_thread_masks():
    """The no-args self-check: the checked-in package satisfies the rule
    the checker documents."""
    checker = _load_checker()
    assert checker.violations() == []


def test_checker_catches_unmasked_kernel(tmp_path):
    """A kernel that never consults a mask — directly or via a helper —
    is flagged; one that reaches a mask through a call chain is not."""
    checker = _load_checker()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "kernels.py").write_text(
        "def _masked_sum(state):\n"
        "    return (state.x * state.pod_valid).sum()\n"
        "def good_kernel(state, graph):\n"
        "    return _masked_sum(state)\n"
        "def bad_kernel(state, graph):\n"
        "    return state.x.sum()\n"           # ignores every mask
        "def armless_kernel(key):\n"
        "    return key\n"                      # no mask-carrying arg
    )
    bad = checker.violations(
        package=pkg,
        entries={"kernels.py": ("good_kernel", "bad_kernel", "armless_kernel")},
    )
    assert any("bad_kernel" in v and "mask" in v for v in bad)
    assert any("armless_kernel" in v and "no mask-carrying" in v for v in bad)
    assert not any("good_kernel" in v for v in bad)


def test_checker_scopes_entry_points_to_their_module(tmp_path):
    """A same-named masked function in ANOTHER module cannot vouch for a
    listed kernel: the entry point must be defined — and masked — in the
    module it is listed under."""
    checker = _load_checker()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "real.py").write_text(
        "def decide(state):\n"
        "    return state.x.sum()\n"            # the listed kernel: unmasked
    )
    (pkg / "other.py").write_text(
        "def decide(state):\n"
        "    return state.pod_valid.sum()\n"    # impostor with the same name
    )
    bad = checker.violations(package=pkg, entries={"real.py": ("decide",)})
    assert any("decide" in v and "mask" in v for v in bad)
    # listing a module that never defines the name is 'not found', even
    # though another module does define it
    bad2 = checker.violations(package=pkg, entries={"real.py": ("helper",)})
    assert any("not found" in v for v in bad2)


def test_checker_flags_missing_entry_point(tmp_path):
    """A listed kernel that does not exist (renamed, deleted) is loud —
    the list cannot silently rot."""
    checker = _load_checker()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text("def real(state):\n    return state.node_valid\n")
    bad = checker.violations(
        package=pkg, entries={"m.py": ("real", "vanished")}
    )
    assert any("vanished" in v and "not found" in v for v in bad)
    assert not any("real(" in v for v in bad)
    bad2 = checker.violations(package=pkg, entries={"gone.py": ("x",)})
    assert any("missing" in v for v in bad2)
