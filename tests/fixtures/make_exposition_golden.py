#!/usr/bin/env python
"""Regenerate exposition_golden.prom — the byte-exact wire-format pin
``tests/test_observability.py::test_exposition_golden_file`` compares
against. Keep the registrations here IDENTICAL to that test's."""

from pathlib import Path

from kubernetes_rescheduling_tpu.telemetry.attribution import (
    publish_attribution,
)
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
    decode_rollup,
    publish_rollup,
    rollup_numpy,
)
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MICRO_BUCKETS,
    MetricsRegistry,
)


def build_registry() -> MetricsRegistry:
    registry = MetricsRegistry()
    registry.counter(
        "rounds_total", "rescheduling rounds executed", labelnames=("algorithm",)
    ).labels(algorithm="communication").inc(3)
    registry.gauge(
        "communication_cost", "cost", labelnames=("algorithm",)
    ).labels(algorithm="communication").set(12.5)
    h = registry.histogram(
        "decision_seconds", "latency", labelnames=("algorithm",),
        buckets=(0.001, 0.01, 0.1),
    ).labels(algorithm="communication")
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    registry.counter("esc_total", "label escaping", labelnames=("p",)).labels(
        p='a"b\\c\nd'
    ).inc()
    publish_attribution(
        registry,
        {
            "total": 10.0,
            "tail": 1.0,
            "edges": [
                {"src_service": "a", "dst_service": "b", "src_node": "n0",
                 "dst_node": "n1", "cost": 6.0},
            ],
            "node_pairs": [["n0", "n1", 12.0], ["n1", "n0", 12.0]],
            "ingress": {"n0": 5.0, "n1": 5.0},
            "egress": {"n0": 5.0, "n1": 5.0},
        },
        top_k=2,
    )
    # the fleet-rollup families render through the same real publisher
    # (a fixed 4-tenant matrix: cost, load_std, degraded, skipped, drift)
    matrix = [
        [10.0, 1.0, 0.0, 0.0, 0.0],
        [40.0, 4.0, 1.0, 0.0, 2.0],
        [20.0, 2.0, 0.0, 0.0, 0.0],
        [30.0, 3.0, 0.0, 1.0, 1.0],
    ]
    publish_rollup(
        registry,
        decode_rollup(rollup_numpy(matrix, top_k=2), top_k=2),
    )
    # the serving plane's documented micro-bucket preset renders through
    # the same histogram path (MICRO_BUCKETS, 50µs–250ms — the preset
    # every serving_request_seconds{stage} family selects at
    # registration); samples straddle below/inside/above the preset
    sr = registry.histogram(
        "serving_request_seconds",
        "per-request serving latency by stage",
        labelnames=("stage",),
        buckets=MICRO_BUCKETS,
    )
    for v, stage in (
        (20e-6, "total"), (300e-6, "total"), (0.004, "total"),
        (0.5, "total"), (120e-6, "queue_wait"),
    ):
        sr.labels(stage=stage).observe(v)
    # the SLO v2 families render through the real history plane + budget
    # engine: a 2-series budget store fed synthetic counters over 4
    # ticks, one evaluation (publishes the budget/burn gauges), then a
    # third family past the hard series budget — exactly one counted LRU
    # eviction (timeseries_evictions_total 1, timeseries_series 2)
    from kubernetes_rescheduling_tpu.telemetry.slo import SloEngine, SloSpec
    from kubernetes_rescheduling_tpu.telemetry.timeseries import SeriesStore

    store = SeriesStore(
        capacity=8, max_series=2, registry=registry,
        families=("ok_total", "bad_total", "spill_total"),
    )
    for tick, (ok, bad) in enumerate(
        ((10, 0), (20, 1), (30, 3), (40, 6)), start=1
    ):
        store.sample(
            [
                {"metric": "ok_total", "type": "counter", "labels": {},
                 "value": float(ok)},
                {"metric": "bad_total", "type": "counter", "labels": {},
                 "value": float(bad)},
            ],
            tick,
        )
    engine = SloEngine(
        (SloSpec(name="golden", objective=0.9,
                 good=(("ok_total", ()),), bad=(("bad_total", ()),)),),
        store, registry=registry,
        budget_window=8, fast_window=4, fast_burn=2.0,
        slow_window=6, slow_burn=1.5,
    )
    engine.evaluate(4)
    store.sample(
        [{"metric": "spill_total", "type": "counter", "labels": {},
          "value": 1.0}],
        5,
    )
    return registry


if __name__ == "__main__":
    out = Path(__file__).parent / "exposition_golden.prom"
    out.write_text(build_registry().expose())
    print(f"wrote {out}")
