"""Fleet mode: vmap-batched multi-tenant solving + the multiplexed
controller loop.

The invariants pinned here are the fleet-mode contract:

- the batched kernel's decisions are BIT-EXACT with the solo decision
  kernel per tenant under shared fold_in seeds (fleet mode changes the
  dispatch shape, never the answer) — on both device planes (vmap and
  the dp shard_map);
- a padded/masked tenant slot never emits a move;
- the batched kernel runs steady state from exactly ONE trace;
- the multiplexed loop keeps per-tenant accounting
  (``max_rounds == records + skipped`` per tenant) and per-tenant
  failure domains: a seeded chaos soak on one tenant leaves every other
  tenant's executed-round counts and comm-cost trajectories identical
  to a no-chaos run;
- solver caches on the boundary are tenant-aware (no cross-pollination,
  no per-round rebuild when tenants alternate over one backend).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.fleet import FleetBackend, make_fleet
from kubernetes_rescheduling_tpu.bench.boundary import BoundaryClient
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
from kubernetes_rescheduling_tpu.config import (
    ChaosConfig,
    FleetConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.solver.fleet import (
    ROW_MOST,
    ROW_SERVICE,
    ROW_TARGET,
    ROW_VICTIM,
    fleet_metrics,
    fleet_solve,
    stack_tenants,
)
from kubernetes_rescheduling_tpu.solver.round_loop import decide
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _mubench_fleet(n=3, seed=0):
    fleet = make_fleet("mubench", n, seed=seed)
    fleet.inject_imbalance()
    return fleet


def _stacked(fleet):
    states = [b.monitor() for b in fleet.backends]
    graphs = [b.comm_graph() for b in fleet.backends]
    return states, graphs, stack_tenants(states), stack_tenants(graphs)


def _keys(n, seed=0):
    return jnp.stack(
        [jax.random.fold_in(jax.random.PRNGKey(seed), t) for t in range(n)]
    )


# ---------------- batched kernel ----------------


@pytest.mark.parametrize("policy", ["communication", "spread", "random"])
def test_fleet_solve_bit_exact_vs_solo(policy):
    """vmap-fleet vs N-solo decision parity, bit-exact under shared
    fold_in seeds — including the PRNG-backed random policy (threefry
    partitionable makes the batched draw equal the solo draw)."""
    fleet = _mubench_fleet(3)
    states, graphs, st, gr = _stacked(fleet)
    pid = jnp.asarray(POLICY_IDS[policy])
    thr = jnp.asarray(30.0)
    keys = _keys(3)
    mask = jnp.ones((3,), bool)
    decisions, hazard = jax.block_until_ready(
        fleet_solve(st, gr, pid, thr, keys, mask)
    )
    decisions, hazard = np.asarray(decisions), np.asarray(hazard)
    for t in range(3):
        most, hz, victim, svc, target = decide(
            states[t], graphs[t], pid, thr, keys[t]
        )
        assert decisions[t, ROW_MOST] == int(most)
        assert decisions[t, ROW_VICTIM] == int(victim)
        assert decisions[t, ROW_SERVICE] == int(svc)
        assert decisions[t, ROW_TARGET] == int(target)
        assert np.array_equal(hazard[t], np.asarray(hz))


def test_fleet_dp_plane_matches_vmap_plane():
    """The dp shard_map plane (one tenant per device) returns the vmap
    plane's outputs bit-exact — the shard body IS the vmap kernel."""
    from kubernetes_rescheduling_tpu.parallel.fleet import fleet_solve_dp

    fleet = _mubench_fleet(4)
    _, _, st, gr = _stacked(fleet)
    pid = jnp.asarray(POLICY_IDS["communication"])
    thr = jnp.asarray(30.0)
    keys = _keys(4)
    mask = jnp.asarray(np.array([True, True, False, True]))
    d1, h1 = jax.block_until_ready(fleet_solve(st, gr, pid, thr, keys, mask))
    d2, h2 = jax.block_until_ready(
        fleet_solve_dp(st, gr, pid, thr, keys, mask)
    )
    assert np.array_equal(np.asarray(d1), np.asarray(d2))
    assert np.array_equal(np.asarray(h1), np.asarray(h2))


def test_padded_tenant_slot_never_emits_moves():
    fleet = _mubench_fleet(3)
    _, _, st, gr = _stacked(fleet)
    pid = jnp.asarray(POLICY_IDS["communication"])
    mask = jnp.asarray(np.array([True, False, True]))
    for rnd in range(1, 4):
        decisions, hazard = fleet_solve(
            st, gr, pid, jnp.asarray(30.0), _keys(3, seed=rnd), mask
        )
        row = np.asarray(decisions)[1]
        # every scalar a no-op, every hazard masked: the padded slot can
        # never produce a MoveRequest whatever its (filler) state says
        assert row[ROW_MOST] == -1
        assert row[ROW_VICTIM] == -1
        assert row[ROW_TARGET] == -1
        assert not np.asarray(hazard)[1].any()


def test_fleet_solve_steady_state_single_trace(registry):
    # a FRESH tenant count (5 — no other test in this module stacks 5
    # mubench tenants) so a jit-cache hit from a sibling test cannot
    # fake the exactly-one-trace assertion
    fleet = _mubench_fleet(5)
    _, _, st, gr = _stacked(fleet)
    pid = jnp.asarray(POLICY_IDS["communication"])
    mask = jnp.ones((5,), bool)
    for rnd in range(5):
        jax.block_until_ready(
            fleet_solve(st, gr, pid, jnp.asarray(30.0), _keys(5, rnd), mask)
        )
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    calls = registry.counter("jax_calls_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_solve").value == 1
    assert calls.labels(fn="fleet_solve").value == 5


def test_stack_tenants_rejects_mismatched_shapes():
    fleet = _mubench_fleet(2)
    states = [b.monitor() for b in fleet.backends]
    small = states[1].replace(pod_node=states[1].pod_node[:-1])
    with pytest.raises(ValueError, match="common capacity"):
        stack_tenants([states[0], small])


def test_fleet_metrics_matches_solo_objectives():
    from kubernetes_rescheduling_tpu.objectives.metrics import (
        communication_cost,
        load_std,
    )

    fleet = _mubench_fleet(3)
    states, graphs, st, gr = _stacked(fleet)
    m = np.asarray(fleet_metrics(st, gr))
    for t in range(3):
        assert m[t, 0] == pytest.approx(
            float(communication_cost(states[t], graphs[t])), rel=1e-6
        )
        assert m[t, 1] == pytest.approx(
            float(load_std(states[t])), rel=1e-6
        )


# ---------------- multiplexed controller ----------------


def test_fleet_controller_matches_n_solo_controllers():
    """The multiplexed loop IS N solo loops on one device plane: same
    per-tenant key derivation, same decisions, same post-round metrics
    (the loop-level twin of the kernel parity pin above)."""
    key = jax.random.PRNGKey(3)
    fleet = _mubench_fleet(3, seed=1)
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=4,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=3),
    )
    res = run_fleet_controller(fleet, cfg, key=key)
    solo_fleet = _mubench_fleet(3, seed=1)
    solo_cfg = RescheduleConfig(
        algorithm="communication", max_rounds=4, sleep_after_action_s=0.0
    )
    for t, (name, backend) in enumerate(solo_fleet):
        solo = run_controller(
            backend, solo_cfg, key=jax.random.fold_in(key, t)
        )
        frounds = res.results[name].rounds
        assert len(solo.rounds) == len(frounds) == 4
        for a, b in zip(solo.rounds, frounds):
            assert (a.most_hazard, a.service, a.target, a.moved) == (
                b.most_hazard, b.service, b.target, b.moved,
            )
            assert a.communication_cost == pytest.approx(
                b.communication_cost, rel=1e-5
            )
            assert a.load_std == pytest.approx(b.load_std, rel=1e-5)


def test_fleet_round_accounting_and_metrics(registry):
    fleet = _mubench_fleet(3)
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=3,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=3),
    )
    res = run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry
    )
    assert res.tenants == ("tenant0", "tenant1", "tenant2")
    assert registry.gauge("fleet_tenants").value == 3
    rounds_c = registry.counter("fleet_rounds_total", labelnames=("tenant",))
    for name, r in res.results.items():
        # per-tenant accounting: every configured round is a record or a
        # counted skip, and the registry twin agrees
        assert len(r.rounds) + r.skipped_rounds == 3
        assert rounds_c.labels(tenant=name).value == len(r.rounds)
    assert res.batched_solves == 3
    assert res.device_solve_s > 0
    assert res.amortized_solve_ms_per_tenant_round > 0


def test_fleet_chaos_isolation_acceptance(registry):
    """The acceptance pin: a seeded chaos soak on tenant 3 leaves the
    other tenants' executed-round counts AND comm-cost trajectories
    identical to a no-chaos run, while tenant 3 itself degrades (counted
    skips, breaker opens) without ever stalling the fleet."""
    key = jax.random.PRNGKey(0)

    def run(chaos: bool):
        fleet = _mubench_fleet(4)
        cfg = RescheduleConfig(
            algorithm="communication",
            max_rounds=14,
            sleep_after_action_s=0.0,
            retry=RetryPolicy(max_attempts=1, base_delay_s=0.01),
            max_consecutive_failures=2,
            breaker_cooldown_rounds=2,
            chaos=ChaosConfig(
                profile="soak" if chaos else "none", seed=5
            ),
            fleet=FleetConfig(
                tenants=4, chaos_tenants=(3,) if chaos else ()
            ),
        )
        return run_fleet_controller(fleet, cfg, key=key, registry=registry)

    clean = run(False)
    chaotic = run(True)
    for name in ("tenant0", "tenant1", "tenant2"):
        a, b = clean.results[name], chaotic.results[name]
        assert len(a.rounds) == len(b.rounds) == 14
        assert a.skipped_rounds == b.skipped_rounds == 0
        assert [r.communication_cost for r in a.rounds] == [
            r.communication_cost for r in b.rounds
        ]
        assert [r.moved for r in a.rounds] == [r.moved for r in b.rounds]
    t3 = chaotic.results["tenant3"]
    # tenant 3 really was on fire: counted skips (open breaker), breaker
    # transitions, absorbed failures — and still zero lost rounds
    assert len(t3.rounds) + t3.skipped_rounds == 14
    assert t3.skipped_rounds > 0
    assert any(tr["to"] == "open" for tr in t3.breaker_transitions)
    assert t3.boundary_failures > 0
    skips = registry.counter(
        "fleet_rounds_skipped_total", labelnames=("tenant",)
    )
    assert skips.labels(tenant="tenant3").value == t3.skipped_rounds


def test_fleet_healthz_block():
    """/healthz grows a per-tenant fleet block, and one tenant's breaker
    state shows there without unhealthying the plane."""
    from kubernetes_rescheduling_tpu.config import ObsConfig
    from kubernetes_rescheduling_tpu.telemetry.server import OpsPlane

    fleet = _mubench_fleet(2)
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=2,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=2),
    )
    ops = OpsPlane.from_config(ObsConfig(serve_port=None)).start()
    try:
        run_fleet_controller(fleet, cfg, key=jax.random.PRNGKey(0), ops=ops)
        payload, healthy = ops.health.snapshot()
        assert healthy
        assert set(payload["fleet"]) == {"tenant0", "tenant1"}
        for row in payload["fleet"].values():
            assert row["rounds"] == 2
            assert row["breaker"] == "closed"
        # the top-level counters see tenant-rounds (ops.observe_round
        # fires per executed tenant-round, the solo plane contract)
        assert payload["rounds"] == 4
        assert payload["last_round_age_s"] is not None
    finally:
        ops.close()


def test_cli_fleet_reschedule(capsys):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    rc = cli_main(
        [
            "reschedule", "--fleet", "2", "--rounds", "2", "--imbalance",
            "--scenario", "mubench", "--seed", "1",
        ]
    )
    assert rc == 0
    import json

    out = json.loads(capsys.readouterr().out)
    assert out["fleet"] == {"tenants": 2, "plane": "vmap"}
    assert set(out["per_tenant"]) == {"tenant0", "tenant1"}
    for row in out["per_tenant"].values():
        assert row["rounds"] + row["skipped_rounds"] == 2
    assert out["batched_solves"] == 2


def test_cli_fleet_rejects_k8s():
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="sim backend"):
        cli_main(["reschedule", "--fleet", "2", "--backend", "k8s"])


def test_cli_fleet_rejects_unsupported_flags():
    """Solver-shaping flags flow into the validated config — --fleet with
    an incompatible combination exits cleanly instead of silently running
    something else; --perf-ledger fails loudly rather than being a no-op."""
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    with pytest.raises(SystemExit, match="greedy"):
        cli_main(["reschedule", "--fleet", "2", "--moves-per-round", "3"])
    # fleet v2: --algorithm global is fleet-legal now; the sparse
    # backend still rejects (per-tenant static block structure)
    with pytest.raises(SystemExit, match="sparse"):
        cli_main(
            ["reschedule", "--fleet", "2", "--algorithm", "global",
             "--solver-backend", "sparse"]
        )
    with pytest.raises(SystemExit, match="perf-ledger"):
        cli_main(
            ["reschedule", "--fleet", "2", "--perf-ledger", "/tmp/x.jsonl"]
        )


@pytest.mark.slow  # heavy fleet variant: the amortization measurement at
# bench-like scale; kernel/loop correctness stays pinned fast by
# test_fleet_solve_bit_exact_vs_solo and the controller parity cases above
def test_fleet_bench_scale_amortization():
    """A shrunk fleet headline cell (8 tenants × 500 svc × 64 nodes): the
    batched dispatch runs from ONE trace across rounds and its decisions
    stay bit-exact with the solo kernel at bench-like scale."""
    from kubernetes_rescheduling_tpu.bench.harness import make_fleet_problem

    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        states, graphs = make_fleet_problem(
            tenants=8, n_services=500, n_nodes=64
        )
        st, gr = stack_tenants(states), stack_tenants(graphs)
        pid = jnp.asarray(POLICY_IDS["communication"])
        mask = jnp.ones((8,), bool)
        for rnd in range(3):
            decisions, _ = jax.block_until_ready(
                fleet_solve(
                    st, gr, pid, jnp.asarray(30.0), _keys(8, rnd), mask
                )
            )
        traces = reg.counter("jax_traces_total", labelnames=("fn",))
        assert traces.labels(fn="fleet_solve").value == 1
        decisions = np.asarray(decisions)
        for t in (0, 5):
            most, _, victim, svc, target = decide(
                states[t], graphs[t], pid, jnp.asarray(30.0), _keys(8, 2)[t]
            )
            assert decisions[t, ROW_MOST] == int(most)
            assert decisions[t, ROW_VICTIM] == int(victim)
            assert decisions[t, ROW_SERVICE] == int(svc)
            assert decisions[t, ROW_TARGET] == int(target)
    finally:
        set_registry(prev)


# ---------------- config & backend surfaces ----------------


def test_fleet_config_validation():
    FleetConfig(tenants=4, plane="dp", chaos_tenants=(0, 3)).validate()
    with pytest.raises(ValueError, match="plane"):
        FleetConfig(plane="pmap").validate()
    with pytest.raises(ValueError, match="out of range"):
        FleetConfig(tenants=2, chaos_tenants=(2,)).validate()
    # fleet v2: the global and proactive planes are fleet-servable now
    RescheduleConfig(
        algorithm="global", fleet=FleetConfig(tenants=2)
    ).validate()
    RescheduleConfig(
        algorithm="proactive", fleet=FleetConfig(tenants=2)
    ).validate()
    RescheduleConfig(
        moves_per_round="all", fleet=FleetConfig(tenants=2)
    ).validate()
    # ... but a greedy multi-move drain stays a solo loop
    with pytest.raises(ValueError, match="greedy"):
        RescheduleConfig(
            moves_per_round=2, fleet=FleetConfig(tenants=2)
        ).validate()
    # the combinations whose decisions or signatures cannot batch keep
    # rejecting, each naming its reason
    with pytest.raises(ValueError, match="sparse"):
        RescheduleConfig(
            algorithm="global", solver_backend="sparse",
            fleet=FleetConfig(tenants=2),
        ).validate()
    with pytest.raises(ValueError, match="move_cost"):
        RescheduleConfig(
            algorithm="global", global_moves_cap=2,
            fleet=FleetConfig(tenants=2),
        ).validate()
    with pytest.raises(ValueError, match="solver_tp"):
        RescheduleConfig(
            algorithm="global", solver_tp=2, fleet=FleetConfig(tenants=2)
        ).validate()
    # the loop enforces the same gate even with the [fleet] block off
    # (tenants=0 validates — but the caller handed it a fleet anyway)
    with pytest.raises(ValueError, match="greedy"):
        run_fleet_controller(
            make_fleet("mubench", 2),
            RescheduleConfig(moves_per_round=2),
        )


def test_fleet_backend_surface():
    fleet = make_fleet("mubench", 2, seed=0)
    assert fleet.num_tenants == 2
    assert fleet.tenant_names == ["tenant0", "tenant1"]
    with pytest.raises(ValueError, match="unique"):
        FleetBackend(backends=fleet.backends, tenant_names=["a", "a"])
    with pytest.raises(ValueError, match="at least one"):
        FleetBackend(backends=[])
    with pytest.raises(ValueError, match=">= 1"):
        make_fleet("mubench", 0)


def test_fleet_config_from_toml(tmp_path):
    f = tmp_path / "cfg.toml"
    f.write_text(
        "algorithm = 'communication'\n"
        "[fleet]\n"
        "tenants = 4\n"
        "plane = 'dp'\n"
        "chaos_tenants = [1, 3]\n"
    )
    cfg = RescheduleConfig.from_toml(f)
    assert cfg.fleet.tenants == 4
    assert cfg.fleet.plane == "dp"
    assert cfg.fleet.chaos_tenants == (1, 3)


# ---------------- tenant-aware solver caches ----------------


def test_solver_cache_is_tenant_aware():
    """Regression (fleet satellite): two tenants multiplexed over ONE
    backend keep separate cache slots — alternating rounds neither
    cross-pollinate one tenant's graph into the other nor evict (and so
    rebuild) each other's entries."""
    fleet = _mubench_fleet(1)
    backend = fleet.backends[0]
    ba = BoundaryClient(backend, tenant="a")
    bb = BoundaryClient(backend, tenant="b")
    ca = ba.solver_cache("sparse_graph")
    cb = bb.solver_cache("sparse_graph")
    assert ca is not cb  # per-tenant slots, same backend
    ca["graph"], ca["value"] = "ga", "va"
    cb["graph"], cb["value"] = "gb", "vb"
    # alternate "rounds": each tenant re-resolves ITS slot, finds its own
    # entry intact (no rebuild), never the other tenant's (no pollution)
    for _ in range(3):
        assert ba.solver_cache("sparse_graph")["value"] == "va"
        assert bb.solver_cache("sparse_graph")["value"] == "vb"
    # distinct cache names are independent too
    assert ba.solver_cache("pod_graph") == {}
    # the solo controller (tenant=None) keeps its own slot
    assert BoundaryClient(backend).solver_cache("sparse_graph") == {}


def test_sparse_graph_cache_not_rebuilt_per_round(monkeypatch):
    """The fleet-motivating symptom pinned at the controller level: a
    multi-round sparse-solver run builds its SparseCommGraph exactly
    once (the cache survives rounds instead of thrashing)."""
    from kubernetes_rescheduling_tpu.core import sparsegraph

    calls = {"n": 0}
    real = sparsegraph.from_comm_graph

    def counting(graph):
        calls["n"] += 1
        return real(graph)

    monkeypatch.setattr(sparsegraph, "from_comm_graph", counting)
    fleet = _mubench_fleet(1)
    cfg = RescheduleConfig(
        algorithm="global",
        max_rounds=2,
        sleep_after_action_s=0.0,
        solver_backend="sparse",
        balance_weight=0.5,
    )
    run_controller(fleet.backends[0], cfg, key=jax.random.PRNGKey(0))
    assert calls["n"] == 1
