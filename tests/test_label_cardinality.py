"""CI twin of ``scripts/check_label_cardinality.py``: the checked-in
package registers NO unbounded-identity label keys (tenant/service/pod)
outside the budget-gated helpers in ``telemetry/fleet_rollup.py`` — the
static half of the cardinality budget (one stray call site would
re-create the O(T) series explosion the budget suppresses) — and the
checker flags every pinned violation shape (``check_bench_schema.py``
convention, including the no-args self-check)."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_label_cardinality.py"
    )
    spec = importlib.util.spec_from_file_location(
        "check_label_cardinality", path
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_label_cardinality", mod)
    spec.loader.exec_module(mod)
    return mod


def test_checked_in_tree_is_clean():
    checker = _load_checker()
    assert checker.violations() == []


def test_flags_tenant_label_outside_allowlist():
    checker = _load_checker()
    src = (
        "reg.counter(\n"
        '    "my_total", "help",\n'
        '    labelnames=("tenant",),\n'
        ").labels(tenant=name).inc()\n"
    )
    bad = checker.scan_source(src, "kubernetes_rescheduling_tpu/bench/x.py")
    assert len(bad) == 1 and "tenant" in bad[0]


def test_flags_positional_labelnames_and_service_pod_keys():
    checker = _load_checker()
    src = 'registry.gauge("g", "h", ("rank", "service"))\n'
    bad = checker.scan_source(src, "kubernetes_rescheduling_tpu/a.py")
    assert len(bad) == 1 and "service" in bad[0]
    src = 'registry.histogram("h", "h", labelnames=["pod"])\n'
    bad = checker.scan_source(src, "kubernetes_rescheduling_tpu/a.py")
    assert len(bad) == 1 and "pod" in bad[0]


def test_flags_unauditable_dynamic_labelnames():
    checker = _load_checker()
    src = 'registry.counter("c", "h", labelnames=keys)\n'
    bad = checker.scan_source(src, "kubernetes_rescheduling_tpu/a.py")
    assert len(bad) == 1 and "literal" in bad[0]


def test_bounded_labels_and_allowlisted_file_pass():
    checker = _load_checker()
    ok = 'registry.counter("c", "h", labelnames=("rank", "dim", "q"))\n'
    assert checker.scan_source(ok, "kubernetes_rescheduling_tpu/a.py") == []
    tenant = 'registry.counter("c", "h", labelnames=("tenant",))\n'
    assert (
        checker.scan_source(
            tenant, "kubernetes_rescheduling_tpu/telemetry/fleet_rollup.py"
        )
        == []
    )
