"""The perf ledger (ISSUE 4 tentpole, layer 2): append-only schema with
monotone seq, rolling-window regression detection firing AND clearing,
the watchdog's perf_regression rule + /healthz surfacing, historical
BENCH/MULTICHIP ingestion, the bench harness writing one entry per
cell, and the `telemetry perf` trend table."""

import contextlib
import io
import json
from pathlib import Path

import pytest

from kubernetes_rescheduling_tpu.config import PerfConfig, RescheduleConfig
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    SLORules,
    Watchdog,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry import perf_ledger as pl

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _fill(ledger, values, metric="decisions_per_sec", better="higher"):
    for i, v in enumerate(values):
        ledger.append(
            metric=metric, value=v, unit="1/s", scenario="mubench/comm",
            device_kind="cpu", digest="t", better=better, run=i,
        )


# ---------------- ledger mechanics ----------------


def test_append_assigns_monotone_seq_and_resumes(tmp_path):
    path = tmp_path / "perf.jsonl"
    _fill(pl.PerfLedger(path), [1.0, 2.0, 3.0])
    # a NEW handle over the same file resumes the sequence, not restarts
    pl.PerfLedger(path).append(
        metric="decisions_per_sec", value=4.0, unit="1/s",
        scenario="mubench/comm", device_kind="cpu", digest="t",
        better="higher",
    )
    seqs = [r["seq"] for r in pl.load_entries(path)]
    assert seqs == [0, 1, 2, 3]
    for rec in pl.load_entries(path):
        assert pl.validate_entry(rec) == []


def test_append_rejects_nan(tmp_path):
    led = pl.PerfLedger(tmp_path / "perf.jsonl")
    with pytest.raises(ValueError, match="non-finite"):
        led.append(
            metric="m", value=float("nan"), scenario="s", device_kind="cpu",
            digest="t",
        )


def test_config_digest_is_order_independent():
    a = pl.config_digest({"x": 1, "y": [2, 3]})
    b = pl.config_digest({"y": [2, 3], "x": 1})
    assert a == b
    assert a != pl.config_digest({"x": 1, "y": [2, 4]})


# ---------------- regression detection ----------------


def test_detector_fires_and_clears_on_synthetic_series(tmp_path):
    """The satellite pin: a seeded regression flips the verdict; a
    recovery reading flips it back."""
    path = tmp_path / "perf.jsonl"
    led = pl.PerfLedger(path)
    _fill(led, [10.0, 10.3, 9.8, 10.1])
    key = "decisions_per_sec@mubench/comm"
    v = pl.detect(led.entries(), threshold_frac=0.2)
    assert v[key]["status"] == "flat"
    _fill(led, [5.0])  # the cliff: decisions/sec halves
    v = pl.detect(led.entries(), threshold_frac=0.2)
    assert v[key]["status"] == "regressed"
    _fill(led, [10.2])  # recovery
    v = pl.detect(led.entries(), threshold_frac=0.2)
    assert v[key]["status"] != "regressed"


def test_detector_directions_and_baselines():
    def series(values, better):
        return [
            {
                "schema": 1, "seq": i, "metric": "m", "value": v, "unit": "u",
                "scenario": "s", "device_kind": "d", "config_digest": "c",
                "better": better,
            }
            for i, v in enumerate(values)
        ]

    # lower-is-better latency: growth = regression, shrink = improvement
    assert pl.detect(series([10, 10, 15], "lower"))["m@s"]["status"] == "regressed"
    assert pl.detect(series([10, 10, 5], "lower"))["m@s"]["status"] == "improved"
    # higher-is-better throughput: the same shape reads the opposite way
    assert pl.detect(series([10, 10, 15], "higher"))["m@s"]["status"] == "improved"
    assert pl.detect(series([10, 10, 5], "higher"))["m@s"]["status"] == "regressed"
    # "best" baseline is stricter than the median for lower-is-better
    vals = [10.0, 8.0, 12.0, 9.9]
    med = pl.detect(series(vals, "lower"), baseline="median")["m@s"]
    best = pl.detect(series(vals, "lower"), baseline="best")["m@s"]
    assert best["baseline"] == 8.0 and med["baseline"] == 10.0
    # a fresh series (not enough history) is never judged
    assert pl.detect(series([3.0], "lower"))["m@s"]["status"] == "fresh"
    with pytest.raises(ValueError):
        pl.detect([], baseline="mean")


# ---------------- watchdog + healthz ----------------


def _verdict(status, key="decisions_per_sec@mubench/comm"):
    return {
        key: {
            "metric": "decisions_per_sec", "scenario": "mubench/comm",
            "device_kind": "cpu", "config_digest": "t", "better": "higher",
            "current": 5.0, "baseline": 10.0, "ratio": 0.5, "n": 5,
            "status": status,
        }
    }


def test_watchdog_perf_rule_fires_counts_and_clears(registry):
    from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger

    logger = StructuredLogger(name="t")
    wd = Watchdog(SLORules(max_retraces=0), registry=registry, logger=logger)
    raised = wd.observe_perf(_verdict("regressed"))
    assert any(v["rule"] == "perf_regression" for v in raised)
    assert not wd.healthy
    fam = registry.counter("perf_regressions_total", labelnames=("metric",))
    assert fam.labels(metric="decisions_per_sec@mubench/comm").value == 1
    # re-observing the SAME regression neither re-counts nor re-raises
    assert wd.observe_perf(_verdict("regressed")) == []
    assert fam.labels(metric="decisions_per_sec@mubench/comm").value == 1
    slo = registry.counter("slo_violations_total", labelnames=("rule",))
    assert slo.labels(rule="perf_regression").value == 1
    # a rebase (next cell binding) must NOT mask the ledger's verdict
    wd.rebase()
    wd.check()
    assert not wd.healthy
    # recovery clears
    wd.observe_perf(_verdict("flat"))
    assert wd.healthy
    events = [r["event"] for r in logger.records]
    assert "slo_violation" in events and "slo_recovered" in events


def test_ops_plane_perf_verdict_flips_healthz(registry):
    from kubernetes_rescheduling_tpu.telemetry.server import HealthState, OpsPlane

    wd = Watchdog(SLORules(max_retraces=0), registry=registry)
    ops = OpsPlane(registry=registry, watchdog=wd, health=HealthState())
    ops.start()
    try:
        payload, healthy = ops.health.snapshot()
        assert healthy
        ops.observe_perf(_verdict("regressed"))
        payload, healthy = ops.health.snapshot()
        assert not healthy
        assert payload["perf"]["verdict"] == "regressed"
        assert payload["perf"]["regressed"] == [
            "decisions_per_sec@mubench/comm"
        ]
        assert any(
            v["rule"] == "perf_regression" for v in payload["slo"]["active"]
        )
        ops.observe_perf(_verdict("flat"))
        payload, healthy = ops.health.snapshot()
        assert healthy and payload["perf"]["verdict"] == "ok"
    finally:
        ops.close()


# ---------------- historical ingestion ----------------


def test_ingest_checked_in_bench_history(tmp_path):
    history = sorted(REPO.glob("BENCH_r0*.json"))
    assert len(history) == 5
    led = pl.PerfLedger(tmp_path / "hist.jsonl")
    recs = pl.ingest_history(history, led)
    assert len(recs) == 5
    assert [r["seq"] for r in led.entries()] == list(range(5))
    for rec in led.entries():
        assert pl.validate_entry(rec) == []
        assert rec["unit"] == "ms" and rec["better"] == "lower"
    # multichip snapshots ingest as dry-run verdicts
    multi = pl.ingest_bench_file(next(iter(sorted(REPO.glob("MULTICHIP_r0*.json")))))
    assert multi and multi[0]["metric"] == "multichip_dryrun_ok"
    # garbage in, nothing out
    junk = tmp_path / "junk.json"
    junk.write_text("{not json")
    assert pl.ingest_bench_file(junk) == []


# ---------------- harness + CLI acceptance ----------------


def test_bench_session_writes_one_ledger_entry_per_cell_and_cli_renders(
    registry, tmp_path
):
    """Acceptance: after a bench session the ledger holds one entry per
    cell, and `telemetry perf` renders the trend table over that ledger
    plus the ingested BENCH_r01–r05 history."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=2,
        rounds=2,
        scenario="mubench",
        out_dir=str(tmp_path),
        seed=5,
        load=LoadGenConfig(requests_per_phase=128, chunk=128),
    )
    run_experiment(cfg)
    ledgers = list(tmp_path.glob("session_*/perf_ledger.jsonl"))
    assert len(ledgers) == 1
    entries = pl.load_entries(ledgers[0])
    assert len(entries) == 2  # one per (algorithm, run) cell
    assert [e["seq"] for e in entries] == [0, 1]
    assert {e["metric"] for e in entries} == {"decisions_per_sec"}
    assert entries[0]["scenario"] == "mubench/communication"
    assert entries[0]["value"] > 0
    # same config digest: the two repeats form ONE comparable series
    assert len({e["config_digest"] for e in entries}) == 1

    history = sorted(REPO.glob("BENCH_r0*.json"))
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main(
            ["telemetry", "perf", str(ledgers[0])] + [str(p) for p in history]
        )
    assert rc == 0
    text = out.getvalue()
    assert "decisions_per_sec@mubench/communication" in text
    assert "device_round_ms_large@large" in text  # the ingested history
    assert "verdict" in text and "regressed:" in text


def test_harness_regression_flips_session_ops_plane(tmp_path, registry):
    """A seeded synthetic regression in the session ledger arms the
    watchdog rule and /healthz reports it after the cell lands."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    ledger_path = tmp_path / "shared_ledger.jsonl"
    led = pl.PerfLedger(ledger_path)
    # seed a history of IMPOSSIBLY fast cells: whatever the real cell
    # measures will read as a regression against it
    for i in range(4):
        led.append(
            metric="decisions_per_sec", value=1e12 + i, unit="1/s",
            scenario="mubench/communication", device_kind="cpu",
            digest="seeded", better="higher",
        )
    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=2,
        scenario="mubench",
        out_dir=str(tmp_path),
        seed=6,
        serve_port=0,
        perf_ledger=str(ledger_path),
        load=LoadGenConfig(requests_per_phase=128, chunk=128),
    )
    # the harness keys cells by ITS config digest — rewrite the seeds to
    # match so they form one series with the real cell
    entries = pl.load_entries(ledger_path)
    import dataclasses as dc

    real_digest = pl.config_digest(
        {
            k: v
            for k, v in dc.asdict(cfg).items()
            if k not in ("out_dir", "session_name")
        }
    )
    ledger_path.write_text(
        "".join(
            json.dumps({**e, "config_digest": real_digest}) + "\n"
            for e in entries
        )
    )
    run_experiment(cfg)
    recs = pl.load_entries(ledger_path)
    assert len(recs) == 5  # 4 seeds + 1 real cell
    verdicts = pl.detect(recs)
    key = "decisions_per_sec@mubench/communication"
    assert verdicts[key]["status"] == "regressed"


def test_detector_disambiguates_colliding_series():
    """Same metric+scenario on two device kinds (or configs) must yield
    TWO verdicts — a regressed one must never be overwritten by its
    healthy sibling."""
    def rec(seq, value, device):
        return {
            "schema": 1, "seq": seq, "metric": "m", "value": value,
            "unit": "u", "scenario": "s", "device_kind": device,
            "config_digest": f"dig-{device}", "better": "lower",
        }

    entries = [rec(i, 10.0, "cpu") for i in range(3)]
    entries += [rec(i, v, "tpu") for i, v in enumerate((10.0, 10.0, 99.0))]
    v = pl.detect(entries)
    assert len(v) == 2
    statuses = {k: x["status"] for k, x in v.items()}
    assert sorted(statuses.values()) == ["flat", "regressed"]
    regressed_key = next(k for k, s in statuses.items() if s == "regressed")
    assert "tpu" in regressed_key  # the qualifier names the real culprit


def test_cli_reschedule_perf_ledger(registry, tmp_path, capsys):
    """The [perf] block's consumer: `reschedule --perf-ledger` appends one
    judged decisions/sec reading per run (repeats form one series)."""
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    ledger = tmp_path / "resched.jsonl"
    for seed in ("1", "2"):
        rc = cli_main(
            [
                "reschedule", "--algorithm", "communication",
                "--rounds", "2", "--imbalance", "--seed", seed,
                "--perf-ledger", str(ledger),
            ]
        )
        assert rc == 0
        capsys.readouterr()
    entries = pl.load_entries(ledger)
    assert len(entries) == 2
    assert [e["seq"] for e in entries] == [0, 1]
    assert entries[0]["scenario"] == "mubench/communication"
    # different seeds, same setup: one comparable series
    assert len({e["config_digest"] for e in entries}) == 1


def test_report_perf_ranks_ingested_history_before_ledger(tmp_path):
    """A ledger sharing the bench-history series with ingested snapshots
    (the BENCH_LEDGER flow) must be judged today-against-history: the
    ledger's newest record is 'current', not the last snapshot file."""
    from kubernetes_rescheduling_tpu.telemetry.report import report_perf

    led = pl.PerfLedger(tmp_path / "led.jsonl")
    # the metric with 4 checked-in snapshots (r01-r04), so the window is
    # deep enough to judge the ledger's newest reading
    led.append(
        metric="global_solve_round_ms_large", value=500.0, unit="ms",
        scenario="large", device_kind="TPU v5 lite0",
        digest="bench-history", better="lower",
    )
    history = sorted(REPO.glob("BENCH_r0*.json"))
    text = report_perf([str(tmp_path / "led.jsonl")] + [str(p) for p in history])
    # the 500 ms ledger reading is current and regressed vs the ~40-77 ms
    # snapshot history — not the other way round
    assert "REGRESSED" in text
    row = text.split("global_solve_round_ms_large@large")[1].splitlines()[0]
    assert "500" in row


# ---------------- config plumbing ----------------


def test_perf_toml_block(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "algorithm = 'communication'\n"
        "[perf]\n"
        "ledger_path = 'x.jsonl'\n"
        "window = 7\n"
        "regression_frac = 0.35\n"
        "baseline = 'best'\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.perf.ledger_path == "x.jsonl"
    assert cfg.perf.window == 7
    assert cfg.perf.regression_frac == 0.35
    assert cfg.perf.baseline == "best"


def test_perf_config_validation():
    with pytest.raises(ValueError, match="baseline"):
        PerfConfig(baseline="mean").validate()
    with pytest.raises(ValueError, match="window"):
        PerfConfig(window=0).validate()
    with pytest.raises(ValueError, match="regression_frac"):
        RescheduleConfig(perf=PerfConfig(regression_frac=-1)).validate()
