"""Shadow plane: trace corpus, adapters, replay backend, head-to-head.

The checked-in fixtures under ``tests/fixtures/shadow/`` are the replay
corpus: Alibaba-style and Borg-style CSV pairs (~250 rows total, irregular
service sizes so comm cost actually depends on placement), a native
``mini.trace.jsonl``, and ``corrupt_trace.jsonl`` (deliberately outside
the schema checker's ``*.trace.jsonl`` glob) carrying every dirty-data
class: NaN readings, over-capacity readings, phantom node references,
broken JSON, unknown kinds, missing fields, bad timestamps.
"""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.backends.replay import ReplayBackend
from kubernetes_rescheduling_tpu.bench.admission import AdmissionGuard
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.sinks import JsonlSink
from kubernetes_rescheduling_tpu.config import (
    ReconcileConfig,
    RescheduleConfig,
    ShadowConfig,
)
from kubernetes_rescheduling_tpu.telemetry.attribution import (
    attribution_consistent,
)
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.report import report_shadow
from kubernetes_rescheduling_tpu.traces import (
    dump_trace_jsonl,
    load_alibaba_csv,
    load_borg_csv,
    load_shadow_trace,
    load_trace_jsonl,
    rounds_to_trace,
    window_state,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger

FIXTURES = Path(__file__).parent / "fixtures" / "shadow"


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        from kubernetes_rescheduling_tpu.telemetry.registry import get_registry

        yield get_registry()
    finally:
        set_registry(prev)


def _metric(registry, name, **labels):
    for rec in registry.snapshot():
        if rec["metric"] == name and (rec.get("labels") or {}) == labels:
            return rec.get("value")
    return None


def _alibaba():
    return load_alibaba_csv(
        FIXTURES / "alibaba_machines.csv", FIXTURES / "alibaba_containers.csv"
    )


def _shadow_cfg(algorithm="global", rounds=4, **kw):
    return RescheduleConfig(
        algorithm=algorithm,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        balance_weight=0.5 if algorithm == "global" else 0.0,
        shadow=ShadowConfig(enabled=True),
        backend="replay",
        **kw,
    )


# ---------------- corpus + adapters ----------------


def test_native_trace_roundtrip(tmp_path):
    t = load_trace_jsonl(FIXTURES / "mini.trace.jsonl")
    assert not t.quarantined
    assert len(t.windows()) == 3
    assert t.node_names == ("n1", "n2", "n3", "n4")
    assert t.service_names == ("sa", "sb", "sc", "sd")
    # declared edges win over the uniform fallback
    g = t.comm_graph()
    i, j = g.names.index("sa"), g.names.index("sb")
    assert float(g.adj[i, j]) == 2.0
    out = dump_trace_jsonl(t, tmp_path / "again.jsonl")
    t2 = load_trace_jsonl(out)
    assert t2.records == t.records


def test_alibaba_adapter_roundtrip(tmp_path):
    t = _alibaba()
    assert not t.quarantined
    assert len(t.windows()) == 5
    assert len(t.node_names) == 5
    assert t.service_names == tuple(f"app_{s}" for s in "abcdef")
    assert all(len(w.pods) == 24 for w in t.windows())
    st = window_state(t, 0)
    assert float(np.asarray(st.node_cpu_cap)[0]) == 4000.0
    # adapter output IS the native form: dump → load is identical
    t2 = load_trace_jsonl(dump_trace_jsonl(t, tmp_path / "a.trace.jsonl"))
    assert t2.records == t.records


def test_borg_adapter_roundtrip(tmp_path):
    t = load_borg_csv(
        FIXTURES / "borg_machine_events.csv", FIXTURES / "borg_task_usage.csv"
    )
    assert not t.quarantined
    assert len(t.windows()) == 3
    assert len(t.node_names) == 4
    assert len(t.service_names) == 5  # one per job
    # normalized capacities scale by the configured units
    st = window_state(t, 0)
    assert float(np.asarray(st.node_cpu_cap)[0]) == 0.5 * 32_000.0
    t2 = load_trace_jsonl(dump_trace_jsonl(t, tmp_path / "b.trace.jsonl"))
    assert t2.records == t.records


def test_load_shadow_trace_detects_formats():
    # a directory holding the alibaba pair auto-detects
    t = load_shadow_trace(FIXTURES)
    assert t.source.startswith("alibaba:")
    # a native file path loads directly
    t2 = load_shadow_trace(FIXTURES / "mini.trace.jsonl")
    assert len(t2.windows()) == 3
    with pytest.raises(ValueError):
        load_shadow_trace(FIXTURES / "mini.trace.jsonl", fmt="borg")


def test_corrupt_rows_quarantine_at_corpus_layer(registry):
    t = load_trace_jsonl(FIXTURES / "corrupt_trace.jsonl", registry=registry)
    # identity-level breakage is dropped and counted by reason...
    assert t.quarantined == {
        "bad_json": 1,
        "unknown_kind": 1,
        "missing_field": 1,
        "bad_timestamp": 1,
    }
    for reason in t.quarantined:
        assert _metric(
            registry, "trace_rows_quarantined_total", reason=reason
        ) == 1
    # ...while value-level poison flows through to the snapshot
    st = window_state(t, 0, registry=registry)
    assert bool(np.isnan(np.asarray(st.pod_cpu)).any())
    # the phantom node reference was repaired to UNASSIGNED and counted
    assert _metric(
        registry, "trace_rows_quarantined_total", reason="unknown_node_ref"
    ) == 1


def test_corrupt_snapshot_rides_the_admission_guard(registry):
    t = load_trace_jsonl(FIXTURES / "corrupt_trace.jsonl", registry=registry)
    guard = AdmissionGuard(ReconcileConfig(), registry=registry)
    admitted = guard.admit(window_state(t, 0, registry=registry))
    assert admitted is not None  # repaired, not rejected
    assert not bool(np.isnan(np.asarray(admitted.pod_cpu)).any())
    assert _metric(
        registry, "admission_quarantined_total", field="pod_cpu", reason="nan"
    ) == 1
    assert _metric(
        registry,
        "admission_quarantined_total",
        field="pod_cpu",
        reason="over_capacity",
    ) == 1


def test_rounds_to_trace_converts_our_own_telemetry(tmp_path, registry):
    rounds = tmp_path / "rounds.jsonl"
    with rounds.open("w") as f:
        for i in range(3):
            f.write(
                json.dumps(
                    {
                        "round": i + 1,
                        "attribution": {
                            "total": 10.0,
                            "ingress": {"n1": 3.0, "n2": 2.0},
                            "egress": {"n1": 2.0, "n2": 3.0},
                        },
                        "applied_moves": [["svc-a", "n2"]],
                    }
                )
                + "\n"
            )
    t = rounds_to_trace([rounds])
    assert len(t.windows()) == 3
    w = t.windows()[0]
    assert w.nodes["n1"]["cpu_used_m"] == 5.0  # ingress + egress
    # each round's applied move lands as that window's placement event
    assert all(
        [p["pod"] for p in w2.placements] == ["svc-a"] for w2 in t.windows()
    )
    # a pods-free corpus is schema tooling input, never a replay input
    with pytest.raises(ValueError):
        ReplayBackend(t)


# ---------------- replay backend ----------------


def test_replay_backend_serves_windows_and_never_mutates(registry):
    t = _alibaba()
    backend = ReplayBackend(t, registry=registry)
    s0 = backend.monitor()
    s1 = backend.monitor()
    assert backend.window == 1
    landed = backend.apply_move(
        MoveRequest(service="app_a", target_node="m_3")
    )
    assert landed == "m_3"  # advisory echo
    assert backend.recommendations[-1]["service"] == "app_a"
    assert _metric(registry, "shadow_recommendations_total") == 1
    # no mutation path exists: the next monitor serves the pristine next
    # window, and re-built windows from the same trace are bit-identical
    s2 = backend.monitor()
    fresh = ReplayBackend(t)
    fresh.monitor(), fresh.monitor()
    ref = fresh.monitor()
    np.testing.assert_array_equal(np.asarray(s2.pod_node), np.asarray(ref.pod_node))
    np.testing.assert_array_equal(np.asarray(s2.pod_cpu), np.asarray(ref.pod_cpu))
    # the tail clamps instead of running out
    for _ in range(10):
        tail = backend.monitor()
    assert backend.exhausted
    np.testing.assert_array_equal(
        np.asarray(tail.pod_node),
        np.asarray(window_state(t, len(t.windows()) - 1).pod_node),
    )
    assert s0.num_pods == s1.num_pods == s2.num_pods  # static shapes


# ---------------- the end-to-end acceptance test ----------------


def test_shadow_end_to_end_acceptance(registry, tmp_path):
    """The ISSUE-11 acceptance path: replay a checked-in external-format
    fixture, recommend with ZERO backend mutations, score finitely and
    sum-consistently with the attribution plane, render the win-rate
    table, and hold the 1-trace / 1-round_end-transfer invariants."""
    t = _alibaba()
    backend = ReplayBackend(t, registry=registry)
    logger = StructuredLogger(name="shadow-e2e")
    sink = JsonlSink(tmp_path / "rounds.jsonl")
    result = run_controller(
        backend,
        _shadow_cfg(rounds=4),
        key=jax.random.PRNGKey(0),
        logger=logger,
        on_round=lambda rec, st: sink.append(rec.as_dict()),
    )
    assert len(result.rounds) == 4

    # recommendations recorded, nothing applied: the replay backend has
    # no mutation path, and every landed echo equals its request
    assert backend.recommendations
    for rec in backend.recommendations:
        assert rec["target"] is not None

    # every scored round is finite and the twin's attribution re-derives
    # its own cost scalar (the attribution plane's audit invariant)
    for r in result.rounds:
        b = r.shadow
        assert b is not None
        for key in ("cost_actual", "cost_shadow", "cost_delta",
                    "load_std_actual", "load_std_shadow", "win_rate"):
            assert np.isfinite(b[key]), (key, b[key])
        assert attribution_consistent(
            b["attribution"], communication_cost=b["cost_shadow"]
        )
        assert b["edges_delta"]

    # the trace's organic churn is baseline, never drift: no divergences
    # charged, no repair moves polluting the shadow ledger
    snap = registry.snapshot()
    assert not any(
        rec["metric"] == "reconcile_divergences_total" for rec in snap
    )

    # ONE round_end transfer per executed round (shadow scoring rides
    # the same bundle), 1 steady-state trace per kernel
    assert _metric(registry, "device_transfers_total", site="round_end") == 4
    assert _metric(
        registry, "jax_traces_total", fn="controller_round_end"
    ) == 1

    # the head-to-head table renders from rounds.jsonl
    table = report_shadow([str(tmp_path / "rounds.jsonl")])
    assert "win_rate" in table
    assert "WIN" in table or "loss" in table
    assert "scored 4 rounds" in table

    # the global solver beats the recorded scheduler on this corpus
    assert result.rounds[-1].shadow["win_rate"] == 1.0
    assert all(b["cost_delta"] > 0 for b in (r.shadow for r in result.rounds))


def test_shadow_recommendations_are_deterministic(registry):
    """Seeded shadow replay determinism pin: bit-identical
    recommendations across two runs."""

    def run():
        backend = ReplayBackend(_alibaba())
        run_controller(
            backend, _shadow_cfg(rounds=2), key=jax.random.PRNGKey(7)
        )
        return backend.recommendations

    assert run() == run()


def test_shadow_greedy_round_marks_intents_advisory(registry):
    """CAR shadow rounds: the ledger adopts the observed (recorded)
    placement at the first diff — the trace's own churn never reads as
    lost moves or drift even though CAR pins with nodeName."""
    backend = ReplayBackend(_alibaba())
    result = run_controller(
        backend,
        _shadow_cfg(algorithm="communication", rounds=3),
        key=jax.random.PRNGKey(0),
    )
    assert len(result.rounds) == 3
    assert not any(
        rec["metric"] == "reconcile_divergences_total"
        for rec in registry.snapshot()
    )
    # scored blocks exist on the CAR path too
    assert all(r.shadow is not None for r in result.rounds)


def test_twin_tracks_observed_for_untouched_pods(registry):
    """The counterfactual diverges by OUR moves alone: the recorded
    scheduler reshuffling pods we never re-homed lands in the twin too;
    only pods a recommendation touched keep our node."""
    from kubernetes_rescheduling_tpu.bench.round_end import RoundCloser
    from kubernetes_rescheduling_tpu.bench.shadow import ShadowPlane

    t = load_trace_jsonl(FIXTURES / "mini.trace.jsonl")
    g = t.comm_graph()
    s0, s1 = window_state(t, 0), window_state(t, 1)

    def arrays(st):
        return {
            "pod_valid": np.asarray(st.pod_valid),
            "pod_node": np.asarray(st.pod_node),
            "pod_service": np.asarray(st.pod_service),
            "node_valid": np.asarray(st.node_valid),
        }

    plane = ShadowPlane(ShadowConfig(enabled=True), registry=registry)
    plane.bind(s0, g, arrays(s0))

    class Rec:
        applied_moves = (("sa", "n3"),)  # we re-home service sa only
        communication_cost = 1.0
        load_std = 0.0
        attribution = None
        shadow = None

    rec = Rec()
    closer = RoundCloser(registry)
    plane.observe_round(
        1, rec, s1, g, closer, arrays=arrays(s1), fresh=True, top_k=0
    )
    obs1 = plane._observed(s1, arrays(s1))
    for name, node in plane.twin.items():
        if name.startswith("sa-"):
            assert node == "n3"  # ours
        else:
            assert node == obs1[name]  # the trace's (moved!) placement
    closer.flush()
    assert rec.shadow is not None
    assert np.isfinite(rec.shadow["cost_shadow"])

    # a recommended node that DIES in the trace releases ownership: the
    # twin adopts the recorded re-placement instead of scoring pods on
    # a dead node (a physically infeasible placement)
    import jax.numpy as jnp

    dead = s1.replace(
        node_valid=jnp.asarray(np.array([True, True, False, True]))  # n3 dies
    )
    rec2 = Rec()
    rec2.applied_moves = ()
    closer2 = RoundCloser(registry)
    plane.observe_round(
        2, rec2, dead, g, closer2, arrays=arrays(dead), fresh=True, top_k=0
    )
    obs_dead = plane._observed(dead, arrays(dead))
    for name in plane.twin:
        if name.startswith("sa-"):
            assert plane.twin[name] == obs_dead[name]  # released to observed
            assert name not in plane._owned
    closer2.flush()


def test_out_of_order_native_rows_are_resorted_and_counted(
    tmp_path, registry
):
    p = tmp_path / "late.jsonl"
    rows = [
        {"kind": "node", "t": 0.0, "node": "n1", "cpu_cap_m": 1000.0},
        {"kind": "pod", "t": 10.0, "pod": "a", "service": "s", "node": "n1"},
        {"kind": "pod", "t": 5.0, "pod": "b", "service": "s", "node": "n1"},
        {"kind": "pod", "t": 10.0, "pod": "c", "service": "s", "node": "n1"},
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    t = load_trace_jsonl(p, registry=registry)
    assert t.quarantined.get("out_of_order") == 1
    assert [w.t for w in t.windows()] == [0.0, 5.0, 10.0]
    # stable: the two t=10 pods stay one window, in file order
    assert [r["pod"] for r in t.windows()[2].pods] == ["a", "c"]
    assert _metric(
        registry, "trace_rows_quarantined_total", reason="out_of_order"
    ) == 1


def test_integer_ids_are_legal_identity(tmp_path, registry):
    """Integer-id corpora (Google clusterdata machine/job ids) use 0
    legitimately — absent/empty quarantines, falsy does not."""
    p = tmp_path / "ints.jsonl"
    rows = [
        {"kind": "node", "t": 0.0, "node": 0, "cpu_cap_m": 1000.0},
        {"kind": "pod", "t": 0.0, "pod": "j0-0", "service": "j0", "node": 0,
         "cpu_m": 100.0, "mem_b": 1e8},
        {"kind": "pod", "t": 0.0, "pod": "", "service": "j0"},  # empty: bad
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    t = load_trace_jsonl(p, registry=registry)
    assert t.quarantined == {"missing_field": 1}
    assert t.node_names == (0,)
    st = window_state(t, 0)
    assert int(np.asarray(st.pod_node)[0]) == 0  # node 0 resolved, not UNASSIGNED


def test_pod_free_windows_are_not_scored(registry):
    """A machine-events-only window (both placements cost 0 by vacuity)
    must not count a free shadow win — plane-level pin, no controller."""
    from kubernetes_rescheduling_tpu.bench.round_end import RoundCloser
    from kubernetes_rescheduling_tpu.bench.shadow import ShadowPlane
    from kubernetes_rescheduling_tpu.traces.corpus import ClusterTrace

    recs = [
        {"kind": "node", "t": 0.0, "node": "n1", "cpu_cap_m": 8000.0,
         "mem_cap_b": 8e9},
        {"kind": "pod", "t": 0.0, "pod": "s0-0", "service": "s0",
         "node": "n1", "cpu_m": 200.0, "mem_b": 1e8},
        # the second window is machine-events only — no pods restated
        {"kind": "node", "t": 60.0, "node": "n1", "alive": True},
    ]
    t = ClusterTrace(records=recs, source="gappy")
    g = t.comm_graph()
    s0, s1 = window_state(t, 0), window_state(t, 1)
    plane = ShadowPlane(ShadowConfig(enabled=True), registry=registry)
    plane.bind(s0, g, None)

    class Rec:
        applied_moves = ()
        communication_cost = 0.0
        load_std = 0.0
        attribution = None
        shadow = None

    rec = Rec()
    closer = RoundCloser(registry)
    plane.observe_round(1, rec, s1, g, closer, arrays=None, fresh=True, top_k=0)
    closer.flush()
    assert rec.shadow is None  # unscored: no vacuous win
    assert plane.scored == 0
    assert _metric(registry, "shadow_rounds_total", outcome="win") is None


def test_shadow_config_validation():
    from kubernetes_rescheduling_tpu.config import ElasticConfig, FleetConfig

    with pytest.raises(ValueError, match="fleet"):
        _shadow_cfg(fleet=FleetConfig(tenants=2)).validate()
    from kubernetes_rescheduling_tpu.config import ChaosConfig

    with pytest.raises(ValueError, match="chaos"):
        _shadow_cfg(chaos=ChaosConfig(profile="soak")).validate()
    with pytest.raises(ValueError, match="churn|RECORDED"):
        _shadow_cfg(elastic=ElasticConfig(profile="steady")).validate()
    with pytest.raises(ValueError, match="placement_unit"):
        _shadow_cfg(placement_unit="pod").validate()
    with pytest.raises(ValueError, match="admission"):
        _shadow_cfg(reconcile=ReconcileConfig(admission=False)).validate()
    with pytest.raises(ValueError, match="win_margin"):
        ShadowConfig(win_margin=1.5).validate()


def test_watchdog_shadow_rule(registry):
    from kubernetes_rescheduling_tpu.telemetry.watchdog import (
        RULE_SHADOW,
        SLORules,
        Watchdog,
    )

    class Rec:
        decision_latency_s = 0.0
        communication_cost = 1.0
        shadow = None

    wd = Watchdog(
        SLORules(shadow_min_win_rate=0.5, min_samples=2), registry=registry
    )
    r = Rec()
    r.shadow = {"scored": 1, "win_rate": 0.0, "cost_delta": -1.0}
    assert not any(v["rule"] == RULE_SHADOW for v in wd.observe_round(r))
    r2 = Rec()
    r2.shadow = {"scored": 2, "win_rate": 0.0, "cost_delta": -1.0}
    raised = wd.observe_round(r2)
    assert any(v["rule"] == RULE_SHADOW for v in raised)
    r3 = Rec()
    r3.shadow = {"scored": 3, "win_rate": 1.0, "cost_delta": 2.0}
    wd.observe_round(r3)
    assert RULE_SHADOW not in wd.active  # recovered


@pytest.mark.slow  # soak-scale variant; the fast pin stays in
# test_shadow_end_to_end_acceptance above (same invariants, 4 rounds)
def test_shadow_long_soak_holds_invariants(registry):
    """A longer replay over a wider synthetic native trace: invariants
    (finite scores, one transfer per round, 1 steady-state trace) hold
    across the whole trace including the clamped tail."""
    recs = []
    for n in range(6):
        recs.append(
            {"kind": "node", "t": 0.0, "node": f"n{n}", "cpu_cap_m": 16000.0,
             "mem_cap_b": 1.6e10, "alive": True}
        )
    for wi in range(24):
        for si in range(8):
            for k in range(3):
                recs.append(
                    {"kind": "pod", "t": float(wi * 60),
                     "pod": f"s{si}-{k}", "service": f"s{si}",
                     "node": f"n{(si * 2 + k + wi * (si % 3)) % 6}",
                     "cpu_m": 200.0 + 30.0 * si + 10.0 * k, "mem_b": 2e8}
                )
    from kubernetes_rescheduling_tpu.traces.corpus import ClusterTrace

    trace = ClusterTrace(records=recs, source="soak")
    backend = ReplayBackend(trace, registry=registry)
    result = run_controller(
        backend, _shadow_cfg(rounds=30), key=jax.random.PRNGKey(1)
    )
    assert len(result.rounds) == 30
    assert all(
        np.isfinite(r.shadow["cost_shadow"]) for r in result.rounds if r.shadow
    )
    assert _metric(registry, "device_transfers_total", site="round_end") == 30
    assert _metric(
        registry, "jax_traces_total", fn="controller_round_end"
    ) == 1
