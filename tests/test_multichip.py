"""The measured multichip harness (``bench.multichip``): the
scan×dp composition is decision-identical to the single-device fleet
scan (telemetry on or off), pays exactly ONE compile and ONE counted
``round_end`` transfer per block, and its ``BENCH_SCENARIO=multichip``
record passes the MULTICHIP schema checker that gates the checked-in
``MULTICHIP_r06+`` snapshots.

Problem sizes here stay in the 24-31 node range (prefix ``mc``) so the
composed kernels compile fresh in this file — the trace pin cannot be
satisfied by another test file's cache entries. All tests run on the 8
forced host devices from conftest."""

import importlib.util
import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.base import device_kind
from kubernetes_rescheduling_tpu.bench import scan as scan_mod
from kubernetes_rescheduling_tpu.bench.harness import make_fleet_problem
from kubernetes_rescheduling_tpu.bench.multichip import (
    bench_multichip,
    decode_fleet_block_dp,
    fleet_scan_rounds_dp,
)
from kubernetes_rescheduling_tpu.parallel.fleet import (
    _fleet_mesh,
    dp_device_names,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.solver.fleet import stack_tenants
from kubernetes_rescheduling_tpu.telemetry import (
    MeshPlane,
    MetricsRegistry,
    get_registry,
    set_registry,
)


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_bench_schema.py"
    )
    spec = importlib.util.spec_from_file_location("check_bench_schema", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_bench_schema", mod)
    spec.loader.exec_module(mod)
    return mod


def _problem(tenants=8, n_services=40, n_nodes=26):
    states, graphs = make_fleet_problem(
        tenants=tenants, n_services=n_services, n_nodes=n_nodes
    )
    st, gr = stack_tenants(states), stack_tenants(graphs)
    keys = jnp.stack(
        [jax.random.fold_in(jax.random.PRNGKey(0), t) for t in range(tenants)]
    )
    return st, gr, keys


def _run_dp(st, gr, keys, *, rounds, mesh=None, start=0):
    return fleet_scan_rounds_dp(
        st,
        gr,
        jnp.asarray(POLICY_IDS["communication"]),
        jnp.asarray(30.0),
        keys,
        jnp.asarray(start, jnp.int32),
        rounds=rounds,
        mesh=mesh,
    )


def test_dp_scan_bit_identical_to_single_device(registry):
    """The dp composition changes WHERE tenants run, never what they
    decide: decisions/hazard/landed bit-exact vs the single-device
    fleet scan, metrics to float tolerance (same ops, sharded layout)."""
    n_nodes = 26
    st, gr, keys = _problem(n_nodes=n_nodes)
    rounds, tenants = 4, 8
    mesh = _fleet_mesh(tenants, None)
    dp = mesh.shape["dp"]
    assert dp == 8  # conftest forces 8 host devices

    flat_dp = np.asarray(_run_dp(st, gr, keys, rounds=rounds, mesh=mesh))
    flat_1 = np.asarray(
        scan_mod.fleet_scan_rounds(
            st,
            gr,
            jnp.asarray(POLICY_IDS["communication"]),
            jnp.asarray(30.0),
            keys,
            jnp.asarray(0, jnp.int32),
            rounds=rounds,
            pinned=True,
        )
    )
    dec_dp, hz_dp, land_dp, met_dp = decode_fleet_block_dp(
        flat_dp, rounds=rounds, tenants=tenants, num_nodes=n_nodes, dp=dp
    )
    dec_1, hz_1, land_1, met_1 = scan_mod.decode_fleet_block(
        flat_1, rounds=rounds, tenants=tenants, num_nodes=n_nodes
    )
    np.testing.assert_array_equal(dec_dp, dec_1)
    np.testing.assert_array_equal(hz_dp, hz_1)
    np.testing.assert_array_equal(land_dp, land_1)
    np.testing.assert_allclose(met_dp, met_1, rtol=1e-5)


def test_dp_scan_identical_with_telemetry_on_and_off(registry):
    """Feeding the device plane is host-side attribution only — the
    SAME flat bundle bytes whether a MeshPlane observes the block or
    nothing does."""
    st, gr, keys = _problem(n_nodes=27)
    rounds, tenants = 4, 8
    mesh = _fleet_mesh(tenants, None)
    bare = np.asarray(_run_dp(st, gr, keys, rounds=rounds, mesh=mesh))
    plane = MeshPlane(
        registry, device_names=dp_device_names(mesh), sample_memory=False
    )
    observed = np.asarray(_run_dp(st, gr, keys, rounds=rounds, mesh=mesh))
    dec, _hz, _land, met = decode_fleet_block_dp(
        observed, rounds=rounds, tenants=tenants, num_nodes=27, dp=8
    )
    plane.observe_block(
        dispatch_s=0.01,
        transfer_bytes=int(observed.nbytes),
        weights=met[..., 0].sum(axis=0),
        rounds=rounds,
    )
    np.testing.assert_array_equal(observed, bare)
    assert plane.health_block()["devices"] == 8


def test_dp_scan_one_trace_one_transfer_per_block(registry):
    """Steady state: ONE ``fleet_scan_rounds_dp`` trace however many
    blocks run, ONE counted ``round_end`` pull per block, and ZERO
    per-round transfer sites (``fleet_decision``/``fleet_metrics`` stay
    silent — the multichip loop has no per-round host reads)."""
    st, gr, keys = _problem(n_nodes=28)
    rounds, tenants = 5, 8  # rounds=5: a cache key unique to this test
    mesh = _fleet_mesh(tenants, None)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    for i in range(3):
        flat = scan_mod.pull_block(
            _run_dp(
                st, gr, keys, rounds=rounds, mesh=mesh, start=i * rounds
            ),
            registry=registry,
        )
    assert fam.labels(site="round_end").value == 3
    assert fam.labels(site="fleet_decision").value == 0
    assert fam.labels(site="fleet_metrics").value == 0
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_scan_rounds_dp").value == 1
    # the byte twin counted exactly the pulled bundles
    by = registry.counter(
        "device_transfer_bytes_total", labelnames=("site",)
    )
    assert by.labels(site="round_end").value == pytest.approx(
        3 * np.asarray(flat).nbytes
    )


def test_decode_fleet_block_dp_validates_divisibility():
    with pytest.raises(ValueError, match="not divisible"):
        decode_fleet_block_dp(
            np.zeros(8, np.float32), rounds=1, tenants=6, num_nodes=4, dp=4
        )


def test_bench_multichip_record_passes_schema(registry, tmp_path):
    """The harness end to end on the forced 8-device mesh: finite
    readings, the dp/device_kind attribution keys, a nested
    device-rollup reading — and the written MULTICHIP_r06-shaped record
    passes ``check_bench_schema.check_file`` (the gate the checked-in
    snapshot must clear)."""
    result = bench_multichip(
        tenants=8,
        n_services=40,
        n_nodes=29,
        rounds=3,
        reps=2,
        registry=registry,
        rtt_ms=0.05,
    )
    assert result["metric"] == "fleet_scan_rounds_per_sec"
    assert result["value"] > 0 and np.isfinite(result["value"])
    ex = result["extra"]
    assert ex["n_devices"] == 8
    assert ex["device_kind"] == device_kind(8)  # cpux8 on the forced mesh
    assert len(ex["devices"]) == 8
    assert ex["rounds_per_block"] == 3
    assert np.isfinite(ex["step_ms_p99"]) and ex["step_ms_p99"] >= 0
    assert ex["imbalance_ratio"] >= 1.0
    nested = result["device_step_reading"]
    assert nested["metric"] == "multichip_device_step_ms_p99"
    assert nested["better"] == "lower"
    assert nested["value"] == ex["step_ms_p99"]
    # the harness made 3 round_end pulls: 1 warm + 2 timed reps
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == 3

    checker = _load_checker()
    assert checker.check_parsed(result, "r06") == []
    record = {
        "n_devices": ex["n_devices"],
        "device_kind": ex["device_kind"],
        "rc": 0,
        "ok": True,
        "measured": True,
        "cmd": "BENCH_SCENARIO=multichip python bench.py",
        "tail": json.dumps(result),
        "parsed": result,
    }
    p = tmp_path / "MULTICHIP_r06.json"
    p.write_text(json.dumps(record, indent=1) + "\n")
    assert checker.check_file(p) == []
