"""The serving plane (ISSUE 18): request-grain placement through the
bounded batcher, serve-vs-batch kernel parity, exact overload
accounting, the micro-bucket stage histograms, the ``serving_p99``
watchdog flip on /healthz, and the POST /place HTTP front."""

import json
import math
import threading
import time
import types
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.bench.loadgen import open_loop_arrivals
from kubernetes_rescheduling_tpu.bench.serve import run_serve_soak
from kubernetes_rescheduling_tpu.config import (
    ObsConfig,
    RescheduleConfig,
    ServingConfig,
)
from kubernetes_rescheduling_tpu.policies.hazard import detect_hazard
from kubernetes_rescheduling_tpu.policies.scoring import (
    POLICY_IDS,
    choose_node,
)
from kubernetes_rescheduling_tpu.serving import (
    OUTCOME_NO_CANDIDATE,
    OUTCOME_PLACED,
    OUTCOME_SHED,
    OUTCOME_TIMEOUT,
    ServingEngine,
    place_batch,
    place_one,
)
from kubernetes_rescheduling_tpu.serving.engine import (
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    SHED_SHUTDOWN,
    STAGES,
)
from kubernetes_rescheduling_tpu.solver.round_loop import finite_guard
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    OpsPlane,
    OpsServer,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.registry import MICRO_BUCKETS


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _metric(registry, name, **labels):
    for rec in registry.snapshot():
        if rec["metric"] == name and (rec.get("labels") or {}) == labels:
            return rec.get("value")
    return None


def _get(port, path):
    """(status, body bytes, headers) without raising on non-200."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read(), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers


def _post(port, path, payload=None, raw=None):
    data = raw if raw is not None else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=data,
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read(), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers


def _engine(registry, scenario="mubench", **kw):
    backend = make_backend(scenario, 0)
    kw.setdefault("config", ServingConfig())
    return ServingEngine(backend, registry=registry, **kw)


def _prestage(engine, services, deadline_ms=0.0):
    """Deterministically enqueue requests into a NOT-yet-running batcher:
    flip the running flag (admission sheds when the engine is stopped),
    submit from threads, and wait until every request is queued. The
    caller then start()s the batcher, which drains the queue in exactly
    ceil(n / max_batch) padded dispatches."""
    engine._running = True
    threads = []
    for svc in services:
        t = threading.Thread(
            target=engine.place,
            args=(svc,),
            kwargs={"deadline_ms": deadline_ms},
            daemon=True,
        )
        t.start()
        threads.append(t)
    deadline = time.time() + 20
    while time.time() < deadline:
        with engine._cond:
            queued = len(engine._queue)
            settled = queued + engine.outcomes.get(OUTCOME_SHED, 0)
        if settled == len(services):
            return threads
        time.sleep(0.005)
    raise AssertionError("prestage never settled")


# ---------------- config surface ----------------


def test_serving_config_validation():
    ServingConfig().validate()
    with pytest.raises(ValueError):
        ServingConfig(max_batch=0).validate()
    with pytest.raises(ValueError):
        ServingConfig(batch_window_ms=-1.0).validate()
    with pytest.raises(ValueError):
        ServingConfig(queue_depth=0).validate()
    with pytest.raises(ValueError):
        ServingConfig(deadline_ms=-5.0).validate()
    with pytest.raises(ValueError):
        ServingConfig(window=1).validate()
    with pytest.raises(ValueError):
        ServingConfig(ring=0).validate()


def test_serving_config_from_toml(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "max_rounds = 2\n"
        "[serving]\n"
        "enabled = true\n"
        "max_batch = 16\n"
        "batch_window_ms = 1.5\n"
        "queue_depth = 128\n"
        "deadline_ms = 100.0\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.serving.enabled
    assert cfg.serving.max_batch == 16
    assert cfg.serving.batch_window_ms == 1.5
    assert cfg.serving.queue_depth == 128
    assert cfg.serving.deadline_ms == 100.0
    cfg.validate()


def test_serving_requires_greedy_algorithm():
    cfg = RescheduleConfig(
        algorithm="global", serving=ServingConfig(enabled=True)
    )
    with pytest.raises(ValueError, match="serving"):
        cfg.validate()


def test_engine_rejects_unknown_policy(registry):
    with pytest.raises(ValueError, match="unknown serving policy"):
        _engine(registry, policy="nope")


# ---------------- open-loop arrival process ----------------


def test_open_loop_arrivals_shape_and_seed():
    a = open_loop_arrivals(200.0, 500, seed=7)
    b = open_loop_arrivals(200.0, 500, seed=7)
    c = open_loop_arrivals(200.0, 500, seed=8)
    assert a.shape == (500,)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert np.all(np.diff(a) >= 0), "arrival offsets must be nondecreasing"
    # mean inter-arrival gap ≈ 1/rate for an exponential process
    assert abs(np.diff(a).mean() - 1 / 200.0) < 1 / 200.0
    with pytest.raises(ValueError):
        open_loop_arrivals(0.0, 10)
    with pytest.raises(ValueError):
        open_loop_arrivals(10.0, -1)


# ---------------- serve-vs-batch kernel parity ----------------


def _kernel_inputs(engine, seqs):
    policy_id = jnp.asarray(POLICY_IDS[engine.policy], jnp.int32)
    threshold = jnp.asarray(30.0, jnp.float32)
    keys = jnp.stack(
        [jax.random.fold_in(jax.random.PRNGKey(0), s) for s in seqs]
    )
    return policy_id, threshold, keys


def test_place_one_matches_choose_node(registry):
    """The serving kernel's scoring half IS the round kernel's: the
    served target equals ``choose_node`` on the same guarded state."""
    engine = _engine(registry)
    policy_id, threshold, keys = _kernel_inputs(engine, [0])
    svc = jnp.asarray(2, jnp.int32)
    _, target, _ = place_one(
        engine.state, engine.graph, policy_id, threshold, svc, keys[0]
    )
    guarded = finite_guard(engine.state)
    _, hazard_mask = detect_hazard(guarded, threshold)
    expected = choose_node(
        policy_id, guarded, engine.graph, svc, hazard_mask, keys[0]
    )
    assert int(target) == int(expected)


def test_place_batch_rows_bit_identical_to_place_one(registry):
    """Every vmapped row must be BIT-identical to the solo kernel on
    that row's (svc, key) — the serve-vs-batch parity pin."""
    engine = _engine(registry)
    n_svc = len(engine.graph.names)
    svcs = jnp.asarray([i % n_svc for i in range(6)], jnp.int32)
    policy_id, threshold, keys = _kernel_inputs(engine, range(6))
    most_b, target_b, bundle_b = place_batch(
        engine.state, engine.graph, policy_id, threshold, svcs, keys
    )
    for i in range(6):
        most_1, target_1, bundle_1 = place_one(
            engine.state, engine.graph, policy_id, threshold,
            svcs[i], keys[i],
        )
        assert int(most_b[i]) == int(most_1)
        assert int(target_b[i]) == int(target_1)
        np.testing.assert_array_equal(
            np.asarray(bundle_b[i]), np.asarray(bundle_1)
        )


def test_served_decision_matches_solo_kernel(registry):
    """End to end through the engine: a served request's node_index is
    bit-identical to ``place_one`` on the same state and folded key."""
    with _engine(registry) as engine:
        svc = engine.graph.names[1]
        result = engine.place(svc)
    assert result.outcome in (OUTCOME_PLACED, OUTCOME_NO_CANDIDATE)
    policy_id, threshold, keys = _kernel_inputs(engine, [result.request_id])
    _, target, _ = place_one(
        engine.state,
        engine.graph,
        policy_id,
        threshold,
        jnp.asarray(engine._svc_index[svc], jnp.int32),
        keys[0],
    )
    assert result.node_index == int(target)
    assert set(result.timings_ms) == set(STAGES)
    assert result.explain is not None
    assert result.explain["service"] == svc
    assert result.explain["chosen"] == result.node


def test_alibaba_fixture_served_parity(registry):
    """Serve admitted snapshots from the checked-in Alibaba shadow
    fixture: every served decision is bit-identical to the batch decide
    kernel on the same admitted state."""
    from pathlib import Path

    from kubernetes_rescheduling_tpu.backends.replay import ReplayBackend
    from kubernetes_rescheduling_tpu.traces import load_alibaba_csv

    fixtures = Path(__file__).parent / "fixtures" / "shadow"
    trace = load_alibaba_csv(
        fixtures / "alibaba_machines.csv", fixtures / "alibaba_containers.csv"
    )
    backend = ReplayBackend(trace)
    engine = ServingEngine(
        backend, config=ServingConfig(max_batch=4), registry=registry
    )
    services = list(engine.graph.names)[:4]
    with engine:
        results = [engine.place(s) for s in services]
    svcs = jnp.asarray(
        [engine._svc_index[s] for s in services], jnp.int32
    )
    policy_id, threshold, keys = _kernel_inputs(
        engine, [r.request_id for r in results]
    )
    _, targets, _ = place_batch(
        engine.state, engine.graph, policy_id, threshold, svcs, keys,
    )
    for r, t in zip(results, np.asarray(targets)):
        assert r.node_index == int(t)
        assert r.outcome in (OUTCOME_PLACED, OUTCOME_NO_CANDIDATE)


# ---------------- snapshot admission ----------------


class _RejectGuard:
    def admit(self, state):
        return None


def test_first_rejected_snapshot_raises(registry):
    backend = make_backend("mubench", 0)
    with pytest.raises(RuntimeError, match="admission guard"):
        ServingEngine(backend, registry=registry, guard=_RejectGuard())


def test_rejected_refresh_keeps_last_good(registry):
    engine = _engine(registry)
    good = engine.state
    engine._guard = _RejectGuard()
    engine.refresh_snapshot()
    assert engine.state is good


# ---------------- batcher determinism & accounting ----------------


def test_dispatch_count_is_ceil_of_queue_over_max_batch(registry):
    """Pre-staged queue of N drains in EXACTLY ceil(N / max_batch)
    coalesced dispatches (the ≤ bound of the acceptance criterion,
    made deterministic by staging before the batcher starts)."""
    engine = _engine(
        registry,
        config=ServingConfig(max_batch=4, queue_depth=64, deadline_ms=0.0),
    )
    services = [engine.graph.names[i % 3] for i in range(10)]
    threads = _prestage(engine, services)
    engine.start()
    for t in threads:
        t.join(timeout=30)
    engine.stop()
    assert engine.dispatches == math.ceil(10 / 4)
    assert engine.outcomes.get(OUTCOME_PLACED, 0) + engine.outcomes.get(
        OUTCOME_NO_CANDIDATE, 0
    ) == 10
    assert engine.submitted == 10
    # batch-size distribution accounts for every dispatched request
    assert sum(k * v for k, v in engine._batch_sizes.items()) == 10
    assert max(engine._batch_sizes) <= 4


def test_queue_full_sheds_are_counted_exactly(registry):
    """Submissions past queue_depth shed immediately with
    reason=queue_full; accounting stays exact across the mix."""
    engine = _engine(
        registry,
        config=ServingConfig(max_batch=8, queue_depth=4, deadline_ms=0.0),
    )
    services = [engine.graph.names[0]] * 7
    threads = _prestage(engine, services)
    assert engine.shed_reasons.get(SHED_QUEUE_FULL, 0) == 3
    engine.start()
    for t in threads:
        t.join(timeout=30)
    engine.stop()
    answered = engine.outcomes.get(OUTCOME_PLACED, 0) + engine.outcomes.get(
        OUTCOME_NO_CANDIDATE, 0
    )
    assert answered == 4
    assert engine.outcomes.get(OUTCOME_SHED, 0) == 3
    assert answered + engine.outcomes[OUTCOME_SHED] == engine.submitted == 7
    assert _metric(registry, "serving_shed_total", reason=SHED_QUEUE_FULL) == 3
    assert (
        _metric(registry, "serving_placements_total", outcome=OUTCOME_SHED)
        == 3
    )


def test_expired_deadlines_complete_timeout_without_dispatch(registry):
    """Requests whose deadline passed by dequeue time complete
    ``timeout`` (counted BOTH as an outcome and as shed reason
    ``deadline``) and never occupy a batch slot."""
    engine = _engine(
        registry, config=ServingConfig(max_batch=8, queue_depth=16)
    )
    services = [engine.graph.names[0]] * 3
    threads = _prestage(engine, services, deadline_ms=20.0)
    time.sleep(0.06)  # let every staged deadline expire
    engine.start()
    for t in threads:
        t.join(timeout=30)
    engine.stop()
    assert engine.outcomes.get(OUTCOME_TIMEOUT, 0) == 3
    assert engine.dispatches == 0
    assert (
        _metric(registry, "serving_placements_total", outcome=OUTCOME_TIMEOUT)
        == 3
    )
    assert _metric(registry, "serving_shed_total", reason="deadline") == 3
    # the summary/healthz view must AGREE with the metric: deadline sheds
    # show in shed_reasons too, not only in serving_shed_total
    assert engine.shed_reasons.get(SHED_DEADLINE, 0) == 3
    summary = engine.summary()
    assert summary["shed"].get(SHED_DEADLINE) == 3
    for entry in engine.ring():
        assert entry["outcome"] == OUTCOME_TIMEOUT
        assert entry["shed_reason"] == SHED_DEADLINE


def test_place_on_stopped_engine_sheds_shutdown(registry):
    engine = _engine(registry)
    result = engine.place(engine.graph.names[0])
    assert result.outcome == OUTCOME_SHED
    assert result.shed_reason == SHED_SHUTDOWN


class _CondProbeOps:
    """An ops stub that checks, from ANOTHER thread, whether the engine's
    _cond is held while observe_serving runs — the admission-shed path
    feeding ops under _cond is the ABBA half of a deadlock against the
    batcher (which takes the ops feed first and _cond second)."""

    def __init__(self, engine):
        self._engine = engine
        self.cond_held_during_feed: list[bool] = []

    def observe_serving(self, summary, requests=None):
        got: list[bool] = []

        def probe():
            acquired = self._engine._cond.acquire(timeout=2)
            if acquired:
                self._engine._cond.release()
            got.append(acquired)

        t = threading.Thread(target=probe)
        t.start()
        t.join()
        self.cond_held_during_feed.append(not got[0])


def test_admission_shed_feeds_ops_after_releasing_cond(registry):
    """The shed paths (shutdown + queue_full) must publish their ops feed
    only AFTER _cond is released: feeding under _cond inverts the lock
    order against the batcher's feed path and deadlocks the plane."""
    engine = _engine(
        registry, config=ServingConfig(max_batch=8, queue_depth=1)
    )
    probe = engine.ops = _CondProbeOps(engine)
    svc = engine.graph.names[0]
    # shutdown shed: the engine was never started
    assert engine.place(svc).shed_reason == SHED_SHUTDOWN
    # queue_full shed: fill the bounded queue with the batcher off, then
    # overflow it synchronously from this thread
    engine._running = True
    t = threading.Thread(target=engine.place, args=(svc,), daemon=True)
    t.start()
    deadline = time.time() + 10
    while time.time() < deadline:
        with engine._cond:
            if len(engine._queue) == 1:
                break
        time.sleep(0.005)
    else:
        raise AssertionError("the queued request never landed")
    assert engine.place(svc).shed_reason == SHED_QUEUE_FULL
    assert probe.cond_held_during_feed == [False, False]
    engine.start()
    t.join(timeout=30)
    engine.stop()


def test_place_unknown_service_raises_before_submit(registry):
    engine = _engine(registry)
    with pytest.raises(ValueError, match="unknown service"):
        engine.place("not-a-service")
    assert engine.submitted == 0


# ---------------- the seeded concurrency soak ----------------


def _soak(registry, n, rate_rps, max_batch, queue_depth=None):
    engine = _engine(
        registry,
        config=ServingConfig(
            max_batch=max_batch,
            queue_depth=queue_depth or max(n, 64),
            deadline_ms=0.0,
        ),
    )
    services = list(engine.graph.names)
    with engine:
        engine.place(services[0])  # warm the compiled trace
        traces0 = place_batch.traces()
        report = run_serve_soak(
            engine, services, open_loop_arrivals(rate_rps, n, seed=0)
        )
    return engine, report, place_batch.traces() - traces0


def test_acceptance_serve_soak_fast(registry):
    """The tier-1 acceptance soak: N threads, open-loop arrivals, exact
    accounting, ≤ ceil(N/B) dispatches, ONE steady-state trace."""
    n, max_batch = 24, 4
    engine, report, steady_traces = _soak(registry, n, 600.0, max_batch)
    assert report["submitted"] == n
    assert (
        report["answered"] + report["shed"] + report["timed_out"] == n
    ), "every submitted request must resolve to exactly one counted outcome"
    assert report["placed"] > 0
    assert report["placements_per_sec"] > 0
    assert report["p99_ms"] >= report["p50_ms"] >= 0
    # coalescing bounds: never more than one dispatch per request, never
    # fewer than a full-batch drain would need (the exact == ceil(N/B)
    # pin lives in test_dispatch_count_is_ceil_of_queue_over_max_batch,
    # where the queue is pre-staged and the count is deterministic)
    assert math.ceil(n / max_batch) <= engine.dispatches <= n
    # padded static shape: the warmed vmapped kernel never retraces
    assert steady_traces == 0
    summary = engine.summary()
    assert summary["submitted"] == n + 1  # the soak plus its warmup request
    assert summary["count"] > 0
    assert summary["p99_ms"] >= summary["p50_ms"]
    assert sum(summary["outcomes"].values()) == n + 1


@pytest.mark.slow  # 200-request high-rate variant; the 24-request soak stays pinned fast in test_acceptance_serve_soak_fast above
def test_serve_soak_long(registry):
    n, max_batch = 200, 8
    engine, report, steady_traces = _soak(registry, n, 800.0, max_batch)
    assert report["answered"] + report["shed"] + report["timed_out"] == n
    assert engine.dispatches <= math.ceil(n / 1)  # sanity: bounded
    assert steady_traces == 0
    assert report["placements_per_sec"] > 0


@pytest.mark.slow  # overload-with-deadline variant; shed/timeout accounting stays pinned fast by test_queue_full_sheds_are_counted_exactly and test_expired_deadlines_complete_timeout_without_dispatch above
def test_serve_soak_overload_counts_shedding(registry):
    """Tiny queue + tight deadline under a hot open-loop rate: the soak
    must show counted shedding and still account exactly."""
    engine = _engine(
        registry,
        config=ServingConfig(max_batch=2, queue_depth=2, deadline_ms=5.0),
    )
    services = list(engine.graph.names)
    n = 120
    with engine:
        engine.place(services[0], deadline_ms=0.0)
        report = run_serve_soak(
            engine,
            services,
            open_loop_arrivals(3000.0, n, seed=1),
            deadline_ms=5.0,
        )
    assert report["answered"] + report["shed"] + report["timed_out"] == n
    assert report["shed"] + report["timed_out"] > 0, (
        "an overloaded open-loop soak must shed or time out visibly"
    )
    for reason, count in report["shed_reasons"].items():
        assert reason in (SHED_QUEUE_FULL, "deadline")
        assert count > 0


# ---------------- metrics & exposition ----------------


def test_serving_metrics_families(registry):
    with _engine(registry) as engine:
        engine.place(engine.graph.names[0])
    recs = registry.snapshot()
    by_name = {}
    for r in recs:
        by_name.setdefault(r["metric"], []).append(r.get("labels") or {})
    stages = {
        lab["stage"]
        for lab in by_name.get("serving_request_seconds", [])
        if "stage" in lab
    }
    assert stages == set(STAGES)
    assert {"outcome": OUTCOME_PLACED} in by_name.get(
        "serving_placements_total", []
    ) or {"outcome": OUTCOME_NO_CANDIDATE} in by_name.get(
        "serving_placements_total", []
    )
    assert "serving_batch_size" in by_name
    assert "serving_inflight" in by_name


def test_serving_exposition_micro_buckets_conformant(registry):
    """The stage histograms expose through the documented MICRO_BUCKETS
    preset and stay wire-format conformant."""
    from test_observability import assert_exposition_conformant

    with _engine(registry) as engine:
        engine.place(engine.graph.names[0])
    text = registry.expose()
    assert_exposition_conformant(text)
    # one +Inf bucket beyond every documented micro bucket, per stage
    total_buckets = text.count('serving_request_seconds_bucket{')
    assert total_buckets == len(STAGES) * (len(MICRO_BUCKETS) + 1)
    assert 'le="5e-05"' in text  # the 50µs floor of the documented preset


def test_ring_is_bounded_and_carries_outcomes(registry):
    engine = _engine(registry, config=ServingConfig(ring=4, deadline_ms=0.0))
    with engine:
        for i in range(6):
            engine.place(engine.graph.names[i % 3])
    ring = engine.ring()
    assert len(ring) == 4  # bounded at config.ring
    assert [e["request_id"] for e in ring] == [2, 3, 4, 5]  # newest last
    for e in ring:
        assert e["outcome"] in (OUTCOME_PLACED, OUTCOME_NO_CANDIDATE)
        assert "total_ms" in e


# ---------------- /healthz + serving_p99 watchdog ----------------


def _summary(count, p99_ms):
    return {
        "submitted": count,
        "completed": count,
        "count": count,
        "rate_rps": 10.0,
        "p50_ms": p99_ms / 2,
        "p95_ms": p99_ms,
        "p99_ms": p99_ms,
        "batch_sizes": {"1": count},
        "dispatches": count,
        "outcomes": {"placed": count},
        "shed": {},
        "inflight": 0,
    }


def test_healthz_serving_p99_flip_and_recover(registry, tmp_path):
    """A serving_p99 violation flips /healthz to 503 (with the serving
    stanza and the violation detail) and a drained window recovers it;
    rule entry dumps a flight-recorder bundle carrying the request ring."""
    obs = ObsConfig(serve_port=0, slo_serving_p99_ms=50.0).validate()
    ops = OpsPlane.from_config(
        obs, registry=registry, bundle_dir=str(tmp_path)
    ).start()
    try:
        port = ops.server.port
        status, body, _ = _get(port, "/healthz")
        assert status == 200
        ops.observe_serving(
            _summary(count=8, p99_ms=120.0),
            requests=[{"request_id": 7, "outcome": "placed"}],
        )
        status, body, _ = _get(port, "/healthz")
        assert status == 503
        doc = json.loads(body)
        assert doc["status"] == "unhealthy"
        assert doc["serving"]["p99_ms"] == 120.0
        active = {v["rule"]: v for v in doc["slo"]["active"]}
        assert "serving_p99" in active
        assert active["serving_p99"]["threshold_ms"] == 50.0
        bundles = list(tmp_path.glob("*serving_p99*"))
        assert bundles, "rule entry must dump a serving_p99 bundle"
        payload = json.loads(bundles[0].read_text())
        assert payload["serving"]["p99_ms"] == 120.0
        assert payload["requests"][0]["request_id"] == 7
        # the drained window recovers the endpoint without a restart
        ops.observe_serving(_summary(count=8, p99_ms=4.0))
        status, body, _ = _get(port, "/healthz")
        assert status == 200
        assert json.loads(body)["serving"]["p99_ms"] == 4.0
        # below min_samples the rule must not judge at all
        ops.watchdog.rebase()
        ops.observe_serving(_summary(count=2, p99_ms=500.0))
        status, _, _ = _get(port, "/healthz")
        assert status == 200
    finally:
        ops.close()


def test_round_and_serving_watchdog_feeds_are_serialized(registry):
    """--place mode feeds the ONE watchdog from two planes at once: the
    controller's round loop and the serving threads. OpsPlane owns the
    serialization (a plane-level lock over EVERY watchdog feed), so a
    mixed concurrent soak must neither corrupt the rolling windows nor
    raise from mid-mutation deque/dict iteration."""
    from kubernetes_rescheduling_tpu.telemetry.watchdog import (
        SLORules,
        Watchdog,
    )

    wd = Watchdog(
        SLORules(
            window=8, min_samples=2, latency_p95_s=10.0, max_retraces=0,
            serving_p99_ms=1000.0,
        ),
        registry=registry,
    )
    ops = OpsPlane(registry=registry, watchdog=wd)
    rounds_n = serve_n = 150
    errors = []

    def round_feeder():
        rec = types.SimpleNamespace(
            decision_latency_s=0.01, communication_cost=10.0,
            degraded=False, round=1,
        )
        for _ in range(rounds_n):
            try:
                ops.observe_round(rec)
            except Exception as e:  # noqa: BLE001 — the test's verdict
                errors.append(e)

    def serve_feeder():
        for _ in range(serve_n):
            try:
                ops.observe_serving(_summary(count=8, p99_ms=5.0))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [
        threading.Thread(target=round_feeder),
        threading.Thread(target=serve_feeder),
        threading.Thread(target=serve_feeder),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert errors == []
    assert ops.health.rounds == rounds_n
    assert ops.health.serving["p99_ms"] == 5.0
    assert wd.healthy


def test_breaker_bundle_carries_serving_ring(registry, tmp_path):
    obs = ObsConfig(serve_port=None).validate()
    ops = OpsPlane.from_config(obs, registry=registry, bundle_dir=str(tmp_path))
    engine = _engine(registry)
    with engine:
        engine.place(engine.graph.names[0])
    ops.bind_serving(engine)
    assert engine.ops is ops
    ops.on_breaker_transition({"to": "open", "from": "closed"})
    bundles = list(tmp_path.glob("*breaker*"))
    assert bundles
    payload = json.loads(bundles[0].read_text())
    ring = payload.get("serving_requests")
    assert ring and ring[-1]["outcome"] in (
        OUTCOME_PLACED, OUTCOME_NO_CANDIDATE,
    )


# ---------------- the POST /place HTTP front ----------------


def test_post_place_endpoint_roundtrip(registry):
    obs = ObsConfig(serve_port=0).validate()
    ops = OpsPlane.from_config(obs, registry=registry)
    engine = _engine(registry).start()
    ops.bind_serving(engine)
    ops.start()
    try:
        port = ops.server.port
        svc = engine.graph.names[0]
        status, body, _ = _post(port, "/place", {"service": svc})
        assert status == 200
        doc = json.loads(body)
        assert doc["service"] == svc
        assert doc["outcome"] in (OUTCOME_PLACED, OUTCOME_NO_CANDIDATE)
        assert set(doc["timings_ms"]) == set(STAGES)
        assert doc["explain"]["policy"] == "communication"
        if doc["outcome"] == OUTCOME_PLACED:
            assert doc["node"] in engine._node_names
            assert doc["explain"]["chosen"] == doc["node"]
        # the serving stanza rides /healthz once requests flow
        status, body, _ = _get(port, "/healthz")
        assert status == 200
        assert json.loads(body)["serving"]["submitted"] >= 1

        status, body, _ = _post(port, "/place", {"service": "nope"})
        assert status == 400
        assert "unknown service" in json.loads(body)["error"]
        status, body, _ = _post(port, "/place", {"deadline_ms": 5})
        assert status == 400
        # non-numeric deadline_ms is a 400, not a handler crash
        status, body, _ = _post(
            port, "/place", {"service": svc, "deadline_ms": [1]}
        )
        assert status == 400
        assert "deadline_ms" in json.loads(body)["error"]
        status, body, _ = _post(
            port, "/place", {"service": svc, "deadline_ms": "soon"}
        )
        assert status == 400
        status, body, _ = _post(port, "/place", payload=[1, 2])
        assert status == 400
        status, body, _ = _post(port, "/place", raw=b"{not json")
        assert status == 400
        status, body, _ = _post(port, "/nope", {"service": svc})
        assert status == 404
        status, _, headers = _get(port, "/place")
        assert status == 405
        assert headers.get("Allow") == "POST"
    finally:
        ops.close()
        engine.stop()


def test_post_place_without_engine_is_503(registry):
    srv = OpsServer(port=0, registry=registry)
    srv.start()
    try:
        status, body, _ = _post(srv.port, "/place", {"service": "s0"})
        assert status == 503
        assert "no serving engine" in json.loads(body)["error"]
    finally:
        srv.stop()


def test_http_request_cardinality_stays_bounded(registry):
    """Scanner probes + serve load must not mint unbounded
    ops_http_requests_total series: the endpoint label set is pinned."""
    obs = ObsConfig(serve_port=0).validate()
    ops = OpsPlane.from_config(obs, registry=registry)
    engine = _engine(registry).start()
    ops.bind_serving(engine)
    ops.start()
    try:
        port = ops.server.port
        svc = engine.graph.names[0]
        for path in (
            "/", "/metrics", "/healthz", "/events", "/tenants",
            "/tenants/acme", "/tenants/zebra", "/favicon.ico",
            "/admin/.env", "/place", "/wp-login.php",
        ):
            _get(port, path)
        _post(port, "/place", {"service": svc})
        _post(port, "/place", {"service": svc})
        _post(port, "/evil", {"service": svc})
        seen = {
            (rec.get("labels") or {}).get("endpoint")
            for rec in registry.snapshot()
            if rec["metric"] == "ops_http_requests_total"
        }
        assert seen == {
            "/", "/metrics", "/healthz", "/events", "/tenants",
            "/tenants/<name>", "/place", "<other>",
        }
        # GET and POST count into the SAME series: 1 GET probe + 2 POSTs
        assert (
            _metric(registry, "ops_http_requests_total", endpoint="/place")
            == 3
        )
    finally:
        ops.close()
        engine.stop()


def test_metrics_scrape_does_not_block_place(registry):
    """A slow /metrics scrape (holding the read lock) must not
    head-of-line-block an in-flight placement request."""
    obs = ObsConfig(serve_port=0).validate()
    ops = OpsPlane.from_config(obs, registry=registry)
    engine = _engine(registry).start()
    ops.bind_serving(engine)
    ops.start()
    try:
        port = ops.server.port
        svc = engine.graph.names[0]
        _post(port, "/place", {"service": svc})  # warm the trace
        with ops.server._read_lock:  # a scrape stuck mid-exposition
            status, body, _ = _post(port, "/place", {"service": svc})
            assert status == 200
            status, _, _ = _get(port, "/healthz")
            assert status == 200
    finally:
        ops.close()
        engine.stop()
