"""ISSUE 9: async pipelined control loop + single-bundle round-end
transfers.

The contract under test: the software-pipelined schedule
(``[controller] pipeline`` / ``--pipeline``) issues the exact sequential
backend call order — so decisions, records, and all accounting are
BIT-IDENTICAL to the sequential loop on the sim backend — while every
executed round closes its reporting through ONE counted ``round_end``
transfer, the breaker drains the pipeline into the sequential path with
zero lost rounds, and the donated device carries (global solver
placement, forecast RLS state) change HBM, never values.
"""

import contextlib
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.controller import (
    _WALL_MS_BUCKETS,
    run_controller,
)
from kubernetes_rescheduling_tpu.config import (
    ChaosConfig,
    ControllerConfig,
    ElasticConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.telemetry import get_registry
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _backend(n_nodes: int, seed: int = 0) -> SimBackend:
    """Node counts in this file stay in the 9-14 range so the
    module-level kernels compile fresh here (trace pins cannot be
    satisfied by another test file's cache entries)."""
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"pl{i}" for i in range(n_nodes)],
        node_cpu_cap_m=20_000.0,
        seed=seed,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    backend.inject_imbalance(backend.node_names[0])
    return backend


# timing-only fields: everything else in rounds.jsonl must be bit-equal
TIMING_FIELDS = {
    "decision_latencies_s", "decision_latency_s", "wall_s", "pipeline",
}


def _strip(rec) -> dict:
    return {k: v for k, v in rec.as_dict().items() if k not in TIMING_FIELDS}


def _run(
    *, pipeline: bool, n_nodes: int, rounds: int = 6,
    algo: str = "communication", churn_profile: str = "none",
    chaos_profile: str = "none", chaos_seed: int = 0,
    retry: RetryPolicy | None = None, max_consecutive_failures: int = 5,
    with_logger: bool = True, seed: int = 0,
):
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=seed,
        chaos=ChaosConfig(profile=chaos_profile, seed=chaos_seed),
        elastic=ElasticConfig(profile=churn_profile, seed=0),
        max_consecutive_failures=max_consecutive_failures,
        retry=retry if retry is not None else RetryPolicy(),
        controller=ControllerConfig(pipeline=pipeline),
    )
    logger = StructuredLogger(name="t") if with_logger else None
    result = run_controller(
        _backend(n_nodes, seed=seed), cfg,
        key=jax.random.PRNGKey(seed), logger=logger,
    )
    return result, logger


# ---------------- bit-identity: pipelined == sequential ----------------


@pytest.mark.parametrize(
    "algo,churn",
    [
        ("communication", "none"),
        ("communication", "diurnal-autoscale"),  # churny: pipeline drains
        ("proactive", "none"),
        pytest.param(
            "proactive", "diurnal-autoscale",
            marks=pytest.mark.slow,  # the churny-drain half stays pinned fast by the communication/diurnal-autoscale case above and the proactive half by proactive/none — this is the combined soak variant
        ),
    ],
)
def test_pipelined_bit_identical_to_sequential(registry, algo, churn):
    """The acceptance invariant: same decisions, same rounds.jsonl
    modulo timing fields — greedy and proactive, static and churny
    (churn rounds drain to the sequential path and must still agree)."""
    seq, seq_log = _run(
        pipeline=False, n_nodes=9, rounds=6, algo=algo, churn_profile=churn
    )
    pl, pl_log = _run(
        pipeline=True, n_nodes=9, rounds=6, algo=algo, churn_profile=churn
    )
    assert len(seq.rounds) == len(pl.rounds)
    assert seq.skipped_rounds == pl.skipped_rounds
    for a, b in zip(seq.rounds, pl.rounds):
        assert _strip(a) == _strip(b)
    # the structured event streams agree too (decision + round payloads;
    # timing keys excluded)
    def events(log):
        out = []
        for r in log.records:
            if r["event"] in ("decision", "round"):
                out.append({
                    k: v for k, v in r.items()
                    if k not in ("ts", "decision_latency_s")
                })
        return out

    assert events(seq_log) == events(pl_log)


def test_pipelined_bit_identical_global_with_donated_carry(registry):
    """Global rounds dispatch the DONATED solver twin under the pipeline
    conditions (no checkpoint/on_round/ops) — placements must still be
    bit-identical to the undonated sequential run."""
    seq, _ = _run(pipeline=False, n_nodes=10, rounds=4, algo="global")
    pl, _ = _run(pipeline=True, n_nodes=10, rounds=4, algo="global")
    for a, b in zip(seq.rounds, pl.rounds):
        assert _strip(a) == _strip(b)
    assert [r.objective_after for r in seq.rounds] == [
        r.objective_after for r in pl.rounds
    ]


# ---------------- chaos: the breaker drains the pipeline ----------------


def test_pipelined_chaos_soak_drains_with_zero_lost_rounds(registry):
    """Breaker opens mid-flight under seeded chaos: the pipelined loop
    must drain into the sequential path, count every skip, finish every
    record, and remain bit-identical to the sequential chaos run (the
    backend call order — and so the per-call fault stream — is the
    same)."""
    kwargs = dict(
        n_nodes=11, rounds=18, chaos_profile="soak", chaos_seed=0,
        retry=RetryPolicy(max_attempts=1),
        max_consecutive_failures=2,
    )
    seq, _ = _run(pipeline=False, **kwargs)
    pl, _ = _run(pipeline=True, **kwargs)
    # the accounting invariant survives the pipeline drain
    assert len(pl.rounds) + pl.skipped_rounds == 18
    assert pl.skipped_rounds == seq.skipped_rounds
    assert pl.skipped_rounds > 0, "chaos soak should open the breaker"
    assert [t["to"] for t in pl.breaker_transitions] == [
        t["to"] for t in seq.breaker_transitions
    ]
    assert "open" in {t["to"] for t in pl.breaker_transitions}
    for a, b in zip(seq.rounds, pl.rounds):
        assert _strip(a) == _strip(b)


# ---------------- single round-end transfer ----------------


def test_single_round_end_transfer_per_executed_round(registry):
    """Every executed round closes through ONE counted ``round_end``
    pull — explain + attribution + cost/load-std ride the same bundle;
    the historical per-diagnostic sites stay at zero. Holds for both
    schedules."""
    rounds = 5
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    _run(pipeline=False, n_nodes=12, rounds=rounds)
    assert fam.labels(site="round_end").value == rounds
    for legacy in ("attribution", "decision_explain", "solver_objectives",
                   "forecast"):
        assert fam.labels(site=legacy).value == 0
    _run(pipeline=True, n_nodes=12, rounds=rounds)
    assert fam.labels(site="round_end").value == 2 * rounds


def test_bare_loop_single_transfer_and_round_end_kernel(registry):
    """The bare loop (no logger/ops) historically paid two uncounted
    scalar syncs per round; now it pays exactly the one counted bundle,
    from one steady-state compile of the round-end kernel."""
    rounds = 4
    result, _ = _run(
        pipeline=False, n_nodes=13, rounds=rounds, with_logger=False
    )
    assert len(result.rounds) == rounds
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == rounds
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="controller_round_end").value == 1
    calls = registry.counter("jax_calls_total", labelnames=("fn",))
    # one dispatch per fresh snapshot: startup + one post-move per round
    # (the startup bundle is the degraded-close fallback, never pulled)
    assert calls.labels(fn="controller_round_end").value == rounds + 1


class _FailOnceMonitor:
    """Wrapper failing exactly one monitor() call (by 1-based index)."""

    def __init__(self, inner, fail_call: int):
        self.inner = inner
        self._calls = 0
        self._fail_call = fail_call

    def monitor(self):
        self._calls += 1
        if self._calls == self._fail_call:
            raise ConnectionError("injected: post-move monitor down")
        return self.inner.monitor()

    def __getattr__(self, name):
        return getattr(self.inner, name)


@pytest.mark.parametrize("pipeline", [False, True])
def test_degraded_round_reuses_cached_bundle(registry, pipeline):
    """A degraded round (failed post-move monitor) closes on the cached
    round-end values of the snapshot it carried — bit-equal to the
    historical re-pull (same state, same kernel), metrics equal to the
    previous round's, and with a logger attached still exactly one
    transfer (the round's fresh explain bundle)."""
    rounds = 4
    backend = _FailOnceMonitor(_backend(14), fail_call=3)  # round 2's post-move
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=rounds,
        sleep_after_action_s=0.0,
        retry=RetryPolicy(max_attempts=1),
        controller=ControllerConfig(pipeline=pipeline),
    )
    logger = StructuredLogger(name="t")
    result = run_controller(
        backend, cfg, key=jax.random.PRNGKey(0), logger=logger
    )
    assert len(result.rounds) == rounds
    degraded = [r for r in result.rounds if r.degraded]
    assert [r.round for r in degraded] == [2]
    # degraded metrics are the carried snapshot's — the values that
    # closed the previous round (the historical loop recomputed exactly
    # these on the same state)
    assert degraded[0].communication_cost == result.rounds[0].communication_cost
    assert degraded[0].load_std == result.rounds[0].load_std
    assert degraded[0].attribution["total"] == pytest.approx(
        result.rounds[0].attribution["total"]
    )
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == rounds


# ---------------- donated carries ----------------


def test_donated_global_solver_matches_and_aliases(registry):
    """``global_assign_donated`` is the same program under the same fn
    label — identical placements (donating a throwaway copy), and its
    captured memory analysis never holds MORE than the undonated twin
    (input→output aliasing can only reduce resident bytes)."""
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig
    from kubernetes_rescheduling_tpu.solver.global_solver import (
        global_assign,
        global_assign_donated,
    )

    backend = _backend(9, seed=3)
    state = backend.monitor()
    graph = backend.comm_graph()
    cfg = GlobalSolverConfig(sweeps=4, balance_weight=0.5)
    key = jax.random.PRNGKey(1)
    plain, info_p = global_assign(state, graph, key, cfg)
    copy = jax.tree_util.tree_map(jnp.array, state)
    donated, info_d = global_assign_donated(copy, graph, key, cfg)
    assert np.array_equal(
        np.asarray(plain.pod_node), np.asarray(donated.pod_node)
    )
    assert float(info_p["objective_after"]) == pytest.approx(
        float(info_d["objective_after"])
    )
    assert global_assign_donated.fn_label == "global_assign"


def test_donated_carry_hbm_capture(registry):
    """The donation satellite's verification: the donated carry is
    genuinely surrendered (XLA deletes the input buffers — input→output
    aliasing is live, so the carry's two generations never co-reside),
    the HBM cost capture still succeeds with donation in the jit kwargs,
    and the jax_hbm_* gauges carry the captured footprint. (CPU's
    ``memory_analysis`` does not model the aliasing in its byte counts —
    on TPU the saving reads directly off ``jax_hbm_temp_bytes`` /
    ``jax_hbm_output_bytes``; here the deletion is the proof the alias
    is active.)"""
    from kubernetes_rescheduling_tpu.forecast.model import (
        forecast_step,
        init_forecast_state,
    )
    from kubernetes_rescheduling_tpu.telemetry import instrument_jit
    from kubernetes_rescheduling_tpu.telemetry.costmodel import get_costbook

    backend = _backend(10, seed=5)
    state = backend.monitor()
    args = (
        jnp.float32(1e-3), jnp.float32(0.0), jnp.float32(4.0),
        jnp.float32(0.9), jnp.float32(0.97),
    )

    def run(label, **jit_kwargs):
        fn = instrument_jit(forecast_step, name=label, **jit_kwargs)
        fst = init_forecast_state(2, state.num_nodes)
        fn(state, fst, *args)
        leaves = [
            leaf for leaf in jax.tree_util.tree_leaves(fst)
            if isinstance(leaf, jax.Array)
        ]
        return get_costbook().get(label), leaves

    plain, plain_leaves = run("fc_hbm_plain_test")
    donated, donated_leaves = run("fc_hbm_donated_test", donate_argnums=(1,))
    # the donated carry's buffers are consumed; the plain twin's survive
    assert all(leaf.is_deleted() for leaf in donated_leaves)
    assert not any(leaf.is_deleted() for leaf in plain_leaves)
    # HBM capture succeeded under donation and landed on the gauges
    assert plain is not None and donated is not None
    for snap, label in ((plain, "fc_hbm_plain_test"),
                        (donated, "fc_hbm_donated_test")):
        for gauge_name, field in (
            ("jax_hbm_output_bytes", "output_bytes"),
            ("jax_hbm_temp_bytes", "temp_bytes"),
            ("jax_hbm_argument_bytes", "argument_bytes"),
        ):
            g = registry.gauge(gauge_name, labelnames=("fn",)).labels(fn=label)
            assert g.value == snap[field]


@pytest.mark.parametrize("pipeline", [
    pytest.param(False, marks=pytest.mark.slow),  # the degraded-round
    # carry-resurrection contract keeps its fast pin in the pipeline=True
    # case below (same donated solve, same bit-exact assert against the
    # donation-off reference); pipeline=False re-proves it with a second
    # ~21 s solver compile
    True,
])
def test_donated_global_carry_survives_degraded_round(registry, pipeline):
    """Post-review regression (confirmed crash): the donated dense solve
    consumes the snapshot's device buffers, and a failed post-move
    monitor carries that snapshot into the NEXT round's solve. The loop
    must resurrect the carry bit-exactly (pass-through aliases + the
    pre-read placement) — so the degraded-round contract survives
    donation, with decisions identical to a donation-off run."""
    def run(donate_carry: bool):
        # n_nodes=10 deliberately matches the donated-carry global test
        # above: the donated solver's compiled signature is shared, so
        # this regression pays only the undonated twin's compile
        backend = _FailOnceMonitor(_backend(10, seed=7), fail_call=3)
        cfg = RescheduleConfig(
            algorithm="global", max_rounds=4, sleep_after_action_s=0.0,
            balance_weight=0.5,
            retry=RetryPolicy(max_attempts=1),
            controller=ControllerConfig(
                pipeline=pipeline, donate_carry=donate_carry
            ),
        )
        return run_controller(backend, cfg, key=jax.random.PRNGKey(7))

    donated = run(True)
    plain = run(False)
    assert [r.degraded for r in donated.rounds] == [False, True, False, False]
    for a, b in zip(donated.rounds, plain.rounds):
        assert _strip(a) == _strip(b)


def test_proactive_forecast_carry_donation_is_transparent(registry):
    """The controller's forecast kernel donates its RLS carry: proactive
    rounds still run, round_info stays populated, and the plane's state
    handle advances every round (the donated input is never reused)."""
    result, _ = _run(pipeline=True, n_nodes=9, rounds=5, algo="proactive")
    assert len(result.rounds) == 5
    assert all(r.forecast is not None for r in result.rounds)
    assert {r.forecast["mode"] for r in result.rounds} <= {
        "cold", "predictive", "degraded"
    }


# ---------------- telemetry: wall clock, depth, overlap ----------------


def test_pipeline_telemetry_and_wall_histogram(registry):
    rounds = 4
    result, _ = _run(pipeline=True, n_nodes=10, rounds=rounds)
    pipelined = [r for r in result.rounds if r.pipeline is not None]
    assert pipelined, "steady-state rounds should carry pipeline telemetry"
    for r in pipelined:
        assert r.pipeline["depth"] == 2
        assert 0.0 <= r.pipeline["overlap_ratio"] <= 1.0
        assert r.wall_s > 0
    assert registry.gauge("pipeline_depth").value == 2
    hist = registry.histogram(
        "wall_round_ms", labelnames=("mode",), buckets=_WALL_MS_BUCKETS
    ).labels(mode="pipelined")
    assert hist.count == len(pipelined)
    seq_result, _ = _run(pipeline=False, n_nodes=10, rounds=rounds)
    assert all(r.pipeline is None for r in seq_result.rounds)
    hist_seq = registry.histogram(
        "wall_round_ms", labelnames=("mode",), buckets=_WALL_MS_BUCKETS
    ).labels(mode="sequential")
    assert hist_seq.count == rounds


def test_watchdog_pipeline_overlap_rule(registry):
    from types import SimpleNamespace

    from kubernetes_rescheduling_tpu.telemetry.watchdog import (
        RULE_PIPELINE,
        SLORules,
        Watchdog,
    )

    wd = Watchdog(
        SLORules(window=4, min_samples=3, pipeline_min_overlap=0.5),
        registry=registry,
    )

    def rec(ratio):
        return SimpleNamespace(
            decision_latency_s=0.001, communication_cost=1.0,
            pipeline={"overlap_ratio": ratio} if ratio is not None else None,
        )

    # sequential rounds never feed the rule
    for _ in range(5):
        wd.observe_round(rec(None))
    assert RULE_PIPELINE not in wd.active
    # healthy overlap
    for _ in range(3):
        wd.observe_round(rec(0.9))
    assert RULE_PIPELINE not in wd.active
    # collapse: the rolling mean drops under the floor
    for _ in range(4):
        wd.observe_round(rec(0.0))
    assert RULE_PIPELINE in wd.active
    assert (
        registry.counter("slo_violations_total", labelnames=("rule",))
        .labels(rule=RULE_PIPELINE).value == 1
    )
    # recovery: the window refills with healthy ratios
    for _ in range(4):
        wd.observe_round(rec(0.95))
    assert RULE_PIPELINE not in wd.active


# ---------------- config / CLI surfaces ----------------


def test_controller_config_validation(tmp_path):
    # only the implemented depth is accepted — telemetry must never
    # report a schedule that did not run
    with pytest.raises(ValueError):
        ControllerConfig(depth=1).validate()
    with pytest.raises(ValueError):
        ControllerConfig(depth=3).validate()
    ControllerConfig(depth=2).validate()
    toml = tmp_path / "cfg.toml"
    toml.write_text(
        "[controller]\npipeline = true\ndepth = 2\n"
    )
    cfg = RescheduleConfig.from_toml(toml)
    assert cfg.controller.pipeline is True
    assert cfg.controller.depth == 2
    with pytest.raises(ValueError):
        from kubernetes_rescheduling_tpu.config import ObsConfig

        ObsConfig(slo_pipeline_min_overlap=1.5).validate()


def test_cli_pipeline_smoke(registry):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main([
            "reschedule", "--pipeline", "--rounds", "2",
            "--scenario", "mubench", "--imbalance",
        ])
    assert rc == 0
    import json

    payload = json.loads(out.getvalue())
    assert len(payload["rounds"]) == 2


# ---------------- fleet: single-bundle decisions + concurrent boundary ----


def _fleet_run(registry, pipeline: bool):
    from kubernetes_rescheduling_tpu.backends.fleet import make_fleet
    from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller

    fleet = make_fleet("mubench", 3, seed=2)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=4,
        sleep_after_action_s=0.0,
        controller=ControllerConfig(pipeline=pipeline),
    )
    return run_fleet_controller(fleet, cfg, key=jax.random.PRNGKey(2))


def test_fleet_single_decision_bundle_transfer(registry):
    """The fleet round's decisions + hazard masks come home in ONE
    counted transfer (historically two: fleet_decision + fleet_hazard),
    and the batched metrics stay one transfer per round."""
    result = _fleet_run(registry, pipeline=False)
    rounds = result.batched_solves
    assert rounds == 4
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="fleet_decision").value == rounds
    assert fam.labels(site="fleet_hazard").value == 0
    assert fam.labels(site="fleet_metrics").value == rounds


def test_fleet_pipelined_bit_identical_per_tenant(registry):
    """Under --pipeline the per-tenant apply/pace/monitor chains run
    concurrently (each tenant owns its backend clock and breaker) — the
    per-tenant round streams must be bit-identical to the sequential
    interleaving."""
    seq = _fleet_run(registry, pipeline=False)
    pl = _fleet_run(registry, pipeline=True)
    assert seq.tenants == pl.tenants
    for name in seq.tenants:
        a, b = seq.results[name], pl.results[name]
        assert len(a.rounds) == len(b.rounds)
        assert a.skipped_rounds == b.skipped_rounds
        for ra, rb in zip(a.rounds, b.rounds):
            assert _strip(ra) == _strip(rb)
    # the fleet round's wall histogram and overlap gauge moved
    hist = registry.histogram(
        "wall_round_ms", labelnames=("mode",), buckets=_WALL_MS_BUCKETS
    ).labels(mode="fleet")
    assert hist.count == 8  # 4 rounds per run, both runs
    assert registry.gauge("pipeline_depth").value == 2
