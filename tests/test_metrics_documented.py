"""CI twin of ``scripts/check_metrics_documented.py``: every metric name
registered in the package appears in OBSERVABILITY.md's inventory table,
and every documented name still exists in code — the operator-facing
metric docs cannot drift from what the ``/metrics`` endpoint serves."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_metrics_documented.py"
    )
    spec = importlib.util.spec_from_file_location("check_metrics_documented", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_metrics_documented", mod)
    spec.loader.exec_module(mod)
    return mod


def test_metric_inventory_matches_code():
    checker = _load_checker()
    assert checker.violations() == []


def test_checker_sees_known_registrations():
    """The regex really finds multi-line registration sites: a few names
    known to be registered across the package must be discovered."""
    checker = _load_checker()
    code = checker.code_metrics()
    for name in (
        "rounds_total",            # bench/controller.py (multi-line call)
        "chaos_faults_total",      # backends/chaos.py
        "span_seconds",            # telemetry/spans.py
        "slo_violations_total",    # telemetry/watchdog.py
        "flight_recorder_dumps_total",  # telemetry/flight_recorder.py
        "ops_http_requests_total",      # telemetry/server.py
    ):
        assert name in code, f"{name} not discovered by the register regex"


def test_checker_catches_undocumented_metric(tmp_path):
    """Doc parsing is scoped to the Metrics inventory table: a metric
    listed elsewhere in the doc does not count as documented."""
    checker = _load_checker()
    doc = tmp_path / "OBS.md"
    doc.write_text(
        "# x\n\n| file | contents |\n|---|---|\n| `not_a_metric` | y |\n\n"
        "**Metrics** table:\n\n| metric | labels |\n|---|---|\n"
        "| `real_total`, `other_seconds` (histogram) | `a` |\n\n"
        "**Spans** follow.\n\n| `stray_total` | z |\n"
    )
    names = checker.documented_metrics(doc)
    assert names == {"real_total", "other_seconds"}
