"""Workmodel parsing and the builtin µBench s0–s19 topology."""

import json

import numpy as np

from kubernetes_rescheduling_tpu.core.topology import (
    dense_200x20,
    inject_imbalance,
    mubench_scenario,
    synthetic_scenario,
)
from kubernetes_rescheduling_tpu.core.workmodel import Workmodel, mubench_workmodel_c

# The undirected closure the reference hardcodes (reference main.py:31-52).
REFERENCE_RELATION = {
    "s0": ["s1", "s3", "s7", "s16"],
    "s1": ["s0", "s2", "s4", "s13", "s15"],
    "s2": ["s1"],
    "s3": ["s0", "s5", "s6", "s8", "s9", "s12"],
    "s4": ["s1"],
    "s5": ["s3", "s14"],
    "s6": ["s3", "s10", "s17"],
    "s7": ["s0", "s19"],
    "s8": ["s3"],
    "s9": ["s3", "s11"],
    "s10": ["s6"],
    "s11": ["s9"],
    "s12": ["s3"],
    "s13": ["s1"],
    "s14": ["s5"],
    "s15": ["s1", "s18"],
    "s16": ["s0"],
    "s17": ["s6"],
    "s18": ["s15"],
    "s19": ["s7"],
}


class TestMubenchWorkmodel:
    def test_relation_matches_reference_dict(self):
        wm = mubench_workmodel_c()
        assert wm.relation() == REFERENCE_RELATION

    def test_graph_symmetric(self):
        g = mubench_workmodel_c().comm_graph()
        adj = np.asarray(g.adj)
        assert np.array_equal(adj, adj.T)
        # 19 undirected edges in workmodelC (tree plus none extra)
        assert adj.sum() / 2 == 19

    def test_cpu_requests(self):
        wm = mubench_workmodel_c()
        assert all(s.cpu_request_millicores == 100 for s in wm.services)


class TestFromDict:
    def test_parse_mubench_grammar(self, tmp_path):
        data = {
            "s0": {
                "external_services": [{"seq_len": 1, "services": ["s1", "s2"]}],
                "cpu-requests": "250m",
                "replicas": 2,
            },
            "s1": {"external_services": [], "cpu-requests": "100m"},
            "s2": {"external_services": [{"services": ["s1"]}]},
        }
        p = tmp_path / "wm.json"
        p.write_text(json.dumps(data))
        wm = Workmodel.from_file(p)
        assert wm.names == ("s0", "s1", "s2")
        assert wm.services[0].cpu_request_millicores == 250
        assert wm.services[0].replicas == 2
        assert wm.relation() == {
            "s0": ["s1", "s2"],
            "s1": ["s0", "s2"],
            "s2": ["s0", "s1"],
        }

    def test_self_edge_dropped(self):
        wm = Workmodel.from_dict(
            {"s0": {"external_services": [{"services": ["s0", "s1"]}]}, "s1": {}}
        )
        assert wm.services[0].callees == ("s1",)


class TestScenarios:
    def test_mubench_imbalanced(self):
        sc = mubench_scenario()
        pod_node = np.asarray(sc.state.pod_node)
        valid = np.asarray(sc.state.pod_valid)
        assert np.all(pod_node[valid] == 0)
        assert sc.state.num_pods == 20

    def test_inject_imbalance(self):
        sc = mubench_scenario(imbalanced=False, seed=1)
        s2 = inject_imbalance(sc.state, node_index=2)
        assert np.all(np.asarray(s2.pod_node)[np.asarray(s2.pod_valid)] == 2)

    def test_dense_200x20(self):
        sc = dense_200x20()
        assert sc.state.num_pods == 200
        assert sc.state.num_nodes == 20
        assert sc.graph.adj.shape[0] == 200

    def test_synthetic_deterministic(self):
        a = synthetic_scenario(n_pods=50, n_nodes=5, seed=7)
        b = synthetic_scenario(n_pods=50, n_nodes=5, seed=7)
        assert np.array_equal(np.asarray(a.state.pod_node), np.asarray(b.state.pod_node))
        assert np.array_equal(np.asarray(a.graph.adj), np.asarray(b.graph.adj))

    def test_powerlaw_has_hubs(self):
        sc = synthetic_scenario(n_pods=500, n_nodes=20, powerlaw=True, seed=3)
        deg = np.asarray(sc.graph.adj).sum(axis=0)
        assert deg.max() >= 4 * np.median(deg[deg > 0])


class TestProcCost:
    def test_parse_cpu_stress(self, tmp_path):
        import json

        data = {
            "s0": {
                "external_services": [{"services": ["s1"]}],
                "internal_service": {"loader": {"cpu_stress": {
                    "run": True, "range_complexity": [100, 100],
                    "thread_pool_size": 1, "trials": 10,
                }}},
                "cpu-requests": "100m",
            },
            "s1": {
                "internal_service": {"loader": {"cpu_stress": {
                    "run": True, "range_complexity": [200, 400],
                    "thread_pool_size": 2, "trials": 20,
                }}},
                "cpu-requests": "100m",
            },
            "s2": {
                "internal_service": {"loader": {"cpu_stress": {"run": False}}},
            },
            "s3": {},  # no loader stanza at all
        }
        p = tmp_path / "wm.json"
        p.write_text(json.dumps(data))
        wm = Workmodel.from_file(p)
        by = {s.name: s for s in wm.services}
        assert by["s0"].proc_cost == 1.0          # the baseline loader
        # mean(200,400)=300 x 20 trials / 2 threads = 3000 -> 3x baseline
        assert by["s1"].proc_cost == 3.0
        assert by["s2"].proc_cost == 0.05         # stress disabled: floor
        assert by["s3"].proc_cost == 1.0          # absent: default

    def test_builtin_is_uniform_baseline(self):
        wm = mubench_workmodel_c()
        assert all(s.proc_cost == 1.0 for s in wm.services)

    def test_reference_workmodel_file_uniform(self, tmp_path):
        """The reference's own workmodelC stanzas (100x10/1 everywhere)
        must all normalize to 1.0 — file and builtin stay equivalent."""
        import json

        stanza = {
            "external_services": [{"services": ["s1"]}],
            "internal_service": {"loader": {"cpu_stress": {
                "run": True, "range_complexity": [100, 100],
                "thread_pool_size": 1, "trials": 10,
            }}},
            "cpu-requests": "100m",
        }
        p = tmp_path / "wm.json"
        p.write_text(json.dumps({"s0": stanza, "s1": dict(stanza, external_services=[])}))
        wm = Workmodel.from_file(p)
        assert [s.proc_cost for s in wm.services] == [1.0, 1.0]
