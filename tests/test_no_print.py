"""CI twin of ``scripts/check_no_print.py``: the library never prints.

All output from ``kubernetes_rescheduling_tpu/`` goes through the
structured logger or the telemetry registry; stdout belongs to the CLI
whose JSON a pipeline consumes."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = Path(__file__).resolve().parent.parent / "scripts" / "check_no_print.py"
    spec = importlib.util.spec_from_file_location("check_no_print", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_no_print", mod)
    spec.loader.exec_module(mod)
    return mod


def test_no_bare_print_outside_cli():
    checker = _load_checker()
    assert checker.violations() == []


def test_checker_catches_a_print(tmp_path):
    checker = _load_checker()
    f = tmp_path / "mod.py"
    f.write_text("def g():\n    print('dbg')  # noqa\n")
    assert checker.find_bare_prints(f) == [2]
