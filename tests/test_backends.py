"""SimBackend dynamics/faults and K8sBackend against fake client objects."""

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends import (
    K8sBackend,
    LoadModel,
    MoveRequest,
    SimBackend,
)
from kubernetes_rescheduling_tpu.backends.k8s import (
    PlacementMechanism,
    exclude_hazard_affinity,
    extract_redeployable_spec,
    merge_affinity,
)
from kubernetes_rescheduling_tpu.core.state import UNASSIGNED
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c


def make_sim(**kw):
    return SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=["worker1", "worker2", "worker3"],
        **kw,
    )


class TestSimBackend:
    def test_load_propagation(self):
        sim = make_sim()
        rps = sim.load.service_rps(sim.workmodel)
        # s0 is the entry; s1 is called by s0; s2 by s1; leaves get flow too
        assert rps["s0"] == sim.load.entry_rps
        assert rps["s1"] == sim.load.entry_rps
        assert rps["s2"] == sim.load.entry_rps
        # s16 called only by s0
        assert rps["s16"] == sim.load.entry_rps

    def test_monitor_snapshot_shapes(self):
        sim = make_sim()
        state = sim.monitor()
        assert state.num_nodes == 3
        assert int(np.asarray(state.pod_valid).sum()) == 20
        assert float(np.asarray(state.pod_cpu).max()) > 0

    def test_apply_move_moves_all_replicas(self):
        sim = make_sim()
        ok = sim.apply_move(MoveRequest(service="s3", target_node="worker2"))
        assert ok
        state = sim.monitor()
        svc3 = [
            i
            for i in range(state.num_pods)
            if bool(state.pod_valid[i]) and int(state.pod_service[i]) == 3
        ]
        assert all(int(state.pod_node[i]) == 1 for i in svc3)
        assert sim.clock_s == sim.reconcile_delay_s

    def test_apply_move_unknown(self):
        sim = make_sim()
        assert not sim.apply_move(MoveRequest(service="nope", target_node="worker1"))
        assert not sim.apply_move(MoveRequest(service="s0", target_node="nope"))

    def test_imbalance_injection(self):
        sim = make_sim()
        sim.inject_imbalance("worker1")
        state = sim.monitor()
        nodes = np.asarray(state.pod_node)[np.asarray(state.pod_valid)]
        assert (nodes == 0).all()

    def test_node_kill_and_reschedule(self):
        sim = make_sim()
        sim.inject_imbalance("worker1")
        sim.kill_node("worker1")
        state = sim.monitor()
        nodes = np.asarray(state.pod_node)[np.asarray(state.pod_valid)]
        assert (nodes == UNASSIGNED).all()
        assert float(state.node_cpu_cap[0]) == 0.0
        placed = sim.schedule_pending()
        assert placed == 20
        state = sim.monitor()
        nodes = np.asarray(state.pod_node)[np.asarray(state.pod_valid)]
        assert set(nodes.tolist()) <= {1, 2}

    def test_cpu_spike_detected(self):
        sim = make_sim(node_cpu_cap_m=100_000.0)
        base = sim.monitor()
        sim.cpu_spike("s0", 50.0)
        spiked = sim.monitor()
        s0 = next(
            i for i in range(base.num_pods)
            if bool(base.pod_valid[i]) and int(base.pod_service[i]) == 0
        )
        assert float(spiked.pod_cpu[s0]) > float(base.pod_cpu[s0]) * 10

    def test_churn_deterministic(self):
        a, b = make_sim(seed=5), make_sim(seed=5)
        a.churn(10)
        b.churn(10)
        np.testing.assert_array_equal(
            np.asarray(a.monitor().pod_node), np.asarray(b.monitor().pod_node)
        )


# ---- fakes for the k8s adapter ----


class ApiError(Exception):
    def __init__(self, status):
        self.status = status


class FakeCluster:
    """Dict-world cluster implementing the client calls the adapter makes."""

    def __init__(self, wm, nodes=("master", "worker1", "worker2")):
        self.wm = wm
        self.nodes = list(nodes)
        self.deployments = {}
        self.pods = {}
        self.deleted_gen = 0
        self.cordoned = set()
        for i, name in enumerate(wm.names):
            node = self.nodes[1 + i % (len(self.nodes) - 1)]
            self.deployments[name] = self._dep_body(name)
            self.pods[f"{name}-pod"] = {"deployment": name, "node": node}

    def _dep_body(self, name):
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default", "labels": {"app": name}},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {
                        "containers": [
                            {
                                "name": name,
                                "image": f"img/{name}:latest",
                                "imagePullPolicy": "Always",
                                "livenessProbe": {"drop": "me"},
                            }
                        ]
                    },
                },
            },
        }

    # CoreV1-ish
    def list_namespaced_pod(self, namespace, watch=False):
        items = [
            p
            for p in self.list_pod_for_all_namespaces()["items"]
            if p["metadata"]["namespace"] == namespace
        ]
        return {"items": items}

    def list_node(self, watch=False):
        return {
            "items": [
                {
                    "metadata": {"name": n},
                    "status": {"capacity": {"cpu": "8", "memory": "16Gi"}},
                }
                for n in self.nodes
            ]
        }

    def list_pod_for_all_namespaces(self, watch=False):
        return {
            "items": [
                {
                    "metadata": {
                        "name": pname,
                        "namespace": "default",
                        "ownerReferences": [
                            {"kind": "ReplicaSet", "name": f"{info['deployment']}-rs"}
                        ],
                    },
                    "spec": {"nodeName": info["node"]},
                    "status": {
                        "containerStatuses": [
                            {"restartCount": info.get("restarts", 0)}
                        ]
                    },
                }
                for pname, info in self.pods.items()
            ]
        }

    # AppsV1-ish
    def read_namespaced_replica_set(self, name, namespace):
        dep = name[: -len("-rs")]
        return {
            "metadata": {"ownerReferences": [{"kind": "Deployment", "name": dep}]}
        }

    def read_namespaced_deployment(self, name, namespace):
        if name not in self.deployments:
            raise ApiError(404)
        return self.deployments[name]

    def delete_namespaced_deployment(self, name, namespace, body=None):
        self.deployments.pop(name, None)
        for pname in [p for p, i in self.pods.items() if i["deployment"] == name]:
            del self.pods[pname]
        self.deleted_gen += 1

    def patch_node(self, name, body):
        if body.get("spec", {}).get("unschedulable"):
            self.cordoned.add(name)
        else:
            self.cordoned.discard(name)

    def create_namespaced_deployment(self, namespace, body):
        name = body["metadata"]["name"]
        self.deployments[name] = body
        spec = body["spec"]["template"]["spec"]
        node = spec.get("nodeName") or (spec.get("nodeSelector") or {}).get(
            "kubernetes.io/hostname"
        )
        if node is None:
            # unpinned: the fake "scheduler" places on the first
            # schedulable (non-cordoned) worker
            node = next(
                (n for n in self.nodes[1:] if n not in self.cordoned), None
            )
        self.pods[f"{name}-pod"] = {"deployment": name, "node": node}

    # CustomObjects-ish
    def list_cluster_custom_object(self, group, version, plural):
        return {
            "items": [
                {"metadata": {"name": n}, "usage": {"cpu": "2000m", "memory": "4Gi"}}
                for n in self.nodes
            ]
        }

    def list_namespaced_custom_object(self, group, version, namespace, plural):
        return {
            "items": [
                {
                    "metadata": {"name": pname},
                    "containers": [{"usage": {"cpu": "150m", "memory": "100Mi"}}],
                }
                for pname in self.pods
            ]
        }


@pytest.fixture
def fake_backend():
    wm = mubench_workmodel_c()
    fc = FakeCluster(wm)
    backend = K8sBackend(
        workmodel=wm,
        core_api=fc,
        apps_api=fc,
        custom_api=fc,
        sleeper=lambda s: None,
    )
    return backend, fc


class TestSimMechanisms:
    def test_affinity_only_lets_simulated_scheduler_choose(self):
        """kubescheduling semantics (reference rescheduling.py:159-171): the
        policy's pick is advisory; the scheduler places on the
        least-allocated non-excluded node."""
        sim = make_sim(seed=1)
        sim.inject_imbalance("worker1")
        # request a pin to the HOT node with affinityOnly: the simulated
        # scheduler must override toward the emptiest candidate instead
        ok = sim.apply_move(
            MoveRequest(
                service="s0",
                target_node="worker1",
                hazard_nodes=("worker1",),
                mechanism="affinityOnly",
            )
        )
        assert ok
        s0_nodes = {pod[1] for pod in sim._pods if pod[0] == 0}
        assert s0_nodes != {0}            # not where the request pointed
        assert 0 not in s0_nodes          # anti-affinity respected

    def test_affinity_only_all_excluded_fails(self):
        sim = make_sim(seed=1)
        ok = sim.apply_move(
            MoveRequest(
                service="s0",
                target_node="worker1",
                hazard_nodes=("worker1", "worker2", "worker3"),
                mechanism="affinityOnly",
            )
        )
        assert not ok

    def test_pinning_mechanisms_honor_target(self):
        sim = make_sim(seed=1)
        for mech in ("nodeName", "nodeSelector"):
            assert sim.apply_move(
                MoveRequest(service="s2", target_node="worker3", mechanism=mech)
            )
            assert {p[1] for p in sim._pods if p[0] == 2} == {2}


def test_harness_k8s_mode_runs_matrix(tmp_path):
    """`bench --backend k8s` — the matrix drives the live-cluster adapter
    (here against the fake client): VERDICT r1 missing #5."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    wm = mubench_workmodel_c()

    class ImbalancedFake(FakeCluster):
        # worker1 hot (50%), worker2 cool (12.5%): hazard on worker1 only
        def list_cluster_custom_object(self, group, version, plural):
            usage = {"master": "1000m", "worker1": "4000m", "worker2": "1000m"}
            return {
                "items": [
                    {"metadata": {"name": n}, "usage": {"cpu": usage[n], "memory": "4Gi"}}
                    for n in self.nodes
                ]
            }

    fc = ImbalancedFake(wm)
    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=2,
        backend="k8s",
        inject_imbalance=False,        # a live cluster can't be cordoned from here
        out_dir=str(tmp_path),
        load=LoadGenConfig(requests_per_phase=256, chunk=256),
        seed=2,
    )
    summary = run_experiment(
        cfg, core_api=fc, apps_api=fc, custom_api=fc, sleeper=lambda s: None
    )
    run = summary["runs"][0]
    assert run["moves"] >= 1           # moves actually hit the (fake) cluster
    # `restarts` = pods recreated by moves (same semantics as sim);
    # `container_crashes` = the reference's restartCount metric, measured
    # as a per-pod delta — 0 here because nothing actually crashed
    assert run["restart_source"] == "derived_from_moves"
    assert run["load"]["during"]["restarts"] >= run["moves"]
    assert run["load"]["during"]["container_crashes"] == 0
    assert run["load"]["after"]["sent"] > 0
    assert run["sim_clock_s"] is None  # live backend has no simulated clock


class TestK8sBackend:
    def test_monitor(self, fake_backend):
        backend, fc = fake_backend
        state = backend.monitor()
        # master excluded (reference podmonitor.py:45)
        assert "master" not in state.node_names
        assert state.num_nodes == 2
        assert int(np.asarray(state.pod_valid).sum()) == 20
        # capacities parsed: 8 cores = 8000m
        assert float(state.node_cpu_cap[0]) == 8000.0
        # per-pod usage parsed: 150m
        assert float(state.pod_cpu[0]) == 150.0
        # base = node usage - tracked pods
        tracked0 = sum(
            150.0
            for i in range(state.num_pods)
            if bool(state.pod_valid[i]) and int(state.pod_node[i]) == 0
        )
        assert float(state.node_base_cpu[0]) == pytest.approx(2000.0 - tracked0)

    def test_apply_move_nodename(self, fake_backend):
        backend, fc = fake_backend
        ok = backend.apply_move(
            MoveRequest(
                service="s3",
                target_node="worker2",
                hazard_nodes=("worker1",),
                mechanism="nodeName",
            )
        )
        assert ok
        body = fc.deployments["s3"]
        spec = body["spec"]["template"]["spec"]
        assert spec["nodeName"] == "worker2"
        assert spec["schedulerName"] == "default-scheduler"
        c = spec["containers"][0]
        assert c["imagePullPolicy"] == "IfNotPresent"
        assert "livenessProbe" not in c  # only kept keys survive
        values = spec["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"][0]["matchExpressions"][0]["values"]
        assert values == ["worker1"]
        assert fc.pods["s3-pod"]["node"] == "worker2"

    def test_apply_move_nodeselector(self, fake_backend):
        backend, fc = fake_backend
        assert backend.apply_move(
            MoveRequest(service="s1", target_node="worker1", mechanism="nodeSelector")
        )
        spec = fc.deployments["s1"]["spec"]["template"]["spec"]
        assert spec["nodeSelector"] == {"kubernetes.io/hostname": "worker1"}
        assert "nodeName" not in spec or spec.get("nodeName") is None

    def test_apply_move_missing_deployment(self, fake_backend):
        backend, _ = fake_backend
        assert not backend.apply_move(
            MoveRequest(service="nope", target_node="worker1")
        )

    def test_mechanism_table_matches_reference(self):
        # reference rescheduling.py:103,135 (nodeSelector), :155,:216 (nodeName),
        # :167-171 (affinity only)
        assert PlacementMechanism["spread"] == "nodeSelector"
        assert PlacementMechanism["binpack"] == "nodeSelector"
        assert PlacementMechanism["random"] == "nodeName"
        assert PlacementMechanism["communication"] == "nodeName"
        assert PlacementMechanism["kubescheduling"] == "affinityOnly"


def test_merge_affinity_extends_lists():
    base = exclude_hazard_affinity(["w1"])
    merged = merge_affinity(base, exclude_hazard_affinity(["w2"]))
    terms = merged["nodeAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"][
        "nodeSelectorTerms"
    ]
    assert len(terms) == 2


def test_extract_spec_defaults():
    body = extract_redeployable_spec({"metadata": {"name": "x"}, "spec": {}})
    assert body["metadata"]["name"] == "x"
    assert body["spec"]["template"]["spec"]["restartPolicy"] == "Always"
    assert body["spec"]["template"]["spec"]["dnsPolicy"] == "ClusterFirst"


class TestRegressionFixes:
    def test_rps_multi_parent_propagation(self):
        # s0->{x,a}, a->b, b->x, x->y: y must see BOTH paths' flow through x
        from kubernetes_rescheduling_tpu.core.workmodel import ServiceSpec, Workmodel

        wm = Workmodel(
            services=(
                ServiceSpec(name="s0", callees=("x", "a")),
                ServiceSpec(name="a", callees=("b",)),
                ServiceSpec(name="b", callees=("x",)),
                ServiceSpec(name="x", callees=("y",)),
                ServiceSpec(name="y"),
            )
        )
        rps = LoadModel(entry_service="s0", entry_rps=100.0).service_rps(wm)
        assert rps["x"] == 200.0
        assert rps["y"] == 200.0

    def test_dead_node_not_a_candidate(self):
        import jax
        import jax.numpy as jnp
        from kubernetes_rescheduling_tpu.policies import POLICY_IDS, choose_node

        sim = make_sim()
        sim.inject_imbalance("worker2")
        sim.kill_node("worker1")
        state = sim.monitor()
        assert not bool(state.node_valid[0])  # dead node invalid in snapshot
        got = choose_node(
            jnp.asarray(POLICY_IDS["spread"]),
            state,
            sim.comm_graph(),
            jnp.asarray(0),
            jnp.zeros((state.num_nodes,), bool),
            jax.random.PRNGKey(0),
        )
        # spread's lex-min tie-break must not pick the dead worker1
        assert state.node_names[int(got)] != "worker1"

    def test_unknown_callee_skipped(self):
        from kubernetes_rescheduling_tpu.core.workmodel import Workmodel

        wm = Workmodel.from_dict(
            {
                "s0": {"external_services": [{"services": ["db-external"]}]},
            }
        )
        graph = wm.comm_graph()  # must not raise
        assert graph.names == ("s0",)

    def test_fractional_threshold(self):
        import jax.numpy as jnp
        from kubernetes_rescheduling_tpu.core.state import ClusterState
        from kubernetes_rescheduling_tpu.policies import detect_hazard

        state = ClusterState.build(
            node_names=["n0"],
            node_cpu_cap=[1000],
            node_mem_cap=[1e9],
            pod_services=[0],
            pod_nodes=[0],
            pod_cpu=[300],  # exactly 30%
            pod_mem=[0],
        )
        most, mask = detect_hazard(state, threshold=30.9)
        assert int(most) == -1  # 30 < 30.9 — must not truncate to 30
        most2, _ = detect_hazard(state, threshold=30.0)
        assert int(most2) == 0


def test_pod_restart_counts(fake_backend):
    """V6 (reference release1.sh:101-102): per-pod restartCount sums, the
    raw data of the crash-delta metric."""
    backend, fc = fake_backend
    counts = backend.pod_restart_counts()
    assert counts is not None and all(v == 0 for v in counts.values())
    pods = list(fc.pods)
    fc.pods[pods[0]]["restarts"] = 2
    fc.pods[pods[1]]["restarts"] = 3
    counts = backend.pod_restart_counts()
    assert counts[pods[0]] == 2 and counts[pods[1]] == 3
    assert sum(counts.values()) == 5

    class Failing:
        def list_pod_for_all_namespaces(self, watch=False):
            raise RuntimeError("api down")

    backend.core_api = Failing()
    assert backend.pod_restart_counts() is None  # harness skips the metric


def test_harness_k8s_measures_crash_restart_delta(tmp_path):
    """Container crashes during the loop show up in the measured per-pod
    delta — the thing a moves-derived count could never see — and surviving
    a concurrent delete+recreate (fresh pods at 0 must not cancel them)."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    wm = mubench_workmodel_c()

    class CrashyFake(FakeCluster):
        # worker1 hot so the loop moves things; every deployment delete
        # coincides with one container crash on an unrelated pod
        def __init__(self, wm):
            super().__init__(wm)
            self.pods["crashy-pod"] = {
                "deployment": "untracked", "node": "worker2", "restarts": 0
            }

        def list_cluster_custom_object(self, group, version, plural):
            usage = {"master": "1000m", "worker1": "4000m", "worker2": "1000m"}
            return {
                "items": [
                    {"metadata": {"name": n}, "usage": {"cpu": usage[n], "memory": "4Gi"}}
                    for n in self.nodes
                ]
            }

        def delete_namespaced_deployment(self, name, namespace, body=None):
            super().delete_namespaced_deployment(name, namespace, body=body)
            self.pods["crashy-pod"]["restarts"] += 1

    fc = CrashyFake(wm)
    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=2,
        backend="k8s",
        inject_imbalance=False,
        out_dir=str(tmp_path),
        load=LoadGenConfig(requests_per_phase=256, chunk=256),
        seed=2,
    )
    summary = run_experiment(
        cfg, core_api=fc, apps_api=fc, custom_api=fc, sleeper=lambda s: None
    )
    run = summary["runs"][0]
    assert run["moves"] >= 1
    # exactly one injected crash per delete, and deletes == services moved
    assert run["load"]["during"]["container_crashes"] == fc.deleted_gen
    assert run["load"]["during"]["restarts"] >= run["moves"]


def test_k8s_inject_imbalance_cordons_and_piles_up(fake_backend):
    """Live-cluster 'Before' construction (reference
    auto_full_pipeline_repeat.sh:48-58): cordon every other worker,
    recreate every Deployment unpinned so the scheduler can only choose
    the target, then uncordon."""
    backend, fc = fake_backend
    assert set(backend.node_names) == {"worker1", "worker2"}
    # target worker2 — NOT the fake scheduler's first pick, so the pile-up
    # can only happen if worker1 was actually cordoned during injection
    backend.inject_imbalance("worker2")
    nodes = {info["node"] for info in fc.pods.values()}
    assert nodes == {"worker2"}           # the pile-up
    assert fc.cordoned == set()           # uncordoned afterwards
    # and the snapshot sees it: every valid pod on worker2
    state = backend.monitor()
    pn = np.asarray(state.pod_node)[np.asarray(state.pod_valid)]
    w2 = state.node_names.index("worker2")
    assert (pn == w2).all()
    # a typo'd target fails loudly instead of cordoning every worker
    with pytest.raises(ValueError, match="unknown node"):
        backend.inject_imbalance("worker-2")


def test_apply_move_strips_previous_pins(fake_backend):
    """A move expresses the CURRENT decision only: a nodeSelector pin and a
    hazard NotIn rule written by one move must not survive into the next
    re-creation (they would override the scheduler on affinityOnly)."""
    backend, fc = fake_backend
    assert backend.apply_move(
        MoveRequest(
            service="s0",
            target_node="worker2",
            hazard_nodes=("worker1",),
            mechanism="nodeSelector",
        )
    )
    spec = fc.deployments["s0"]["spec"]["template"]["spec"]
    assert spec["nodeSelector"] == {"kubernetes.io/hostname": "worker2"}
    assert "worker1" in str(spec["affinity"])
    # now an unpinned re-create: old selector AND old hostname rule gone
    assert backend.apply_move(
        MoveRequest(service="s0", target_node="worker1", mechanism="affinityOnly")
    )
    spec = fc.deployments["s0"]["spec"]["template"]["spec"]
    assert spec.get("nodeSelector") is None
    assert "worker1" not in str(spec.get("affinity") or {})
    # the fake scheduler chose freely (first schedulable worker)
    assert fc.pods["s0-pod"]["node"] == "worker1"


def test_harness_k8s_inject_imbalance(tmp_path):
    """The matrix's cordon-style Before state now works in k8s mode too —
    the same inject_imbalance call shape as the simulator."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    wm = mubench_workmodel_c()
    fc = FakeCluster(wm)
    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=1,
        backend="k8s",
        inject_imbalance=True,
        out_dir=str(tmp_path),
        load=LoadGenConfig(requests_per_phase=256, chunk=256),
        seed=3,
    )
    summary = run_experiment(
        cfg, core_api=fc, apps_api=fc, custom_api=fc, sleeper=lambda s: None
    )
    run = summary["runs"][0]
    # the Before snapshot measured the pile-up the injection created
    assert run["before"]["load_std"] > 0
    assert run["load"]["before"]["sent"] > 0
