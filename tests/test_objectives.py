"""Objectives vs the pure-Python oracle (SURVEY.md §4 metric-parity tests)."""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.topology import (
    mubench_scenario,
    state_from_workmodel,
    synthetic_scenario,
)
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu import oracle
from kubernetes_rescheduling_tpu.objectives import (
    communication_cost,
    communication_cost_deployment,
    load_std,
    capacity_violation,
    objective_summary,
)


def random_mubench_state(seed):
    wm = mubench_workmodel_c()
    return state_from_workmodel(wm, seed=seed), wm


@pytest.mark.parametrize("seed", range(5))
def test_comm_cost_matches_oracle_single_replica(seed):
    state, wm = random_mubench_state(seed)
    graph = wm.comm_graph()
    snap = oracle.to_snapshot(state, graph)
    expected = oracle.communication_cost(snap, wm.relation())
    got_pairs = float(communication_cost(state, graph))
    got_dep = float(communication_cost_deployment(state, graph))
    assert got_pairs == pytest.approx(expected)
    assert got_dep == pytest.approx(expected)


def test_comm_cost_zero_when_colocated():
    scn = mubench_scenario(imbalanced=True)
    assert float(communication_cost(scn.state, scn.graph)) == 0.0
    assert float(communication_cost_deployment(scn.state, scn.graph)) == 0.0


def test_comm_cost_counts_cross_node_edges():
    # two communicating services on different nodes -> one edge -> cost 1
    from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph

    graph = CommGraph.from_relation({"a": ["b"], "b": ["a"]})
    state = ClusterState.build(
        node_names=["n0", "n1"],
        node_cpu_cap=[1000, 1000],
        node_mem_cap=[1e9, 1e9],
        pod_services=[0, 1],
        pod_nodes=[0, 1],
        pod_cpu=[100, 100],
        pod_mem=[0, 0],
    )
    assert float(communication_cost(state, graph)) == 1.0
    assert float(communication_cost_deployment(state, graph)) == 1.0


@pytest.mark.parametrize("seed", range(5))
def test_load_std_matches_oracle(seed):
    state, wm = random_mubench_state(seed)
    graph = wm.comm_graph()
    snap = oracle.to_snapshot(state, graph)
    assert float(load_std(state)) == pytest.approx(oracle.node_std(snap), rel=1e-5)


def test_capacity_violation():
    from kubernetes_rescheduling_tpu.core.state import ClusterState

    state = ClusterState.build(
        node_names=["n0", "n1"],
        node_cpu_cap=[100, 1000],
        node_mem_cap=[1e9, 1e9],
        pod_services=[0, 0],
        pod_nodes=[0, 0],
        pod_cpu=[150, 50],
        pod_mem=[0, 0],
    )
    assert float(capacity_violation(state)) == pytest.approx(100.0)


def test_objective_summary_padded_scenario():
    scn = synthetic_scenario(n_pods=50, n_nodes=5, seed=1)
    s = objective_summary(scn.state, scn.graph)
    assert set(s) == {
        "communication_cost",
        "load_std",
        "capacity_violation",
        "max_cpu_pct",
    }
    assert float(s["communication_cost"]) >= 0.0


def test_padding_does_not_change_metrics():
    wm = mubench_workmodel_c()
    a = state_from_workmodel(wm, seed=3)
    b = state_from_workmodel(wm, seed=3, node_capacity=8, pod_capacity=64)
    ga = wm.comm_graph()
    gb = wm.comm_graph(capacity=32)
    assert float(communication_cost(a, ga)) == pytest.approx(
        float(communication_cost(b, gb))
    )
    assert float(load_std(a)) == pytest.approx(float(load_std(b)), rel=1e-5)
