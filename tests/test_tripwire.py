"""ISSUE 16: in-block tripwires — device-side health detection for the
scanned schedules.

The contracts under test:

- **Trip-free bit-identity** — with the tripwire plane armed (the
  default) and no rule firing, scanned records AND event streams stay
  bit-identical to the sequential loop and to the tripwires-compiled-out
  scanned run, at ONE counted ``round_end`` transfer per block, one
  steady-state compile per tripwire variant.
- **In-trace detection** — a cost blowup / a non-finite state / a
  same-hazard-node streak trips INSIDE the ``lax.scan`` at the round the
  host-side simulation of the rules predicts; the replay commits exactly
  the rounds BEFORE the trip; the tripped round drains to the per-round
  path under ``scan_drains_total{reason="tripwire"}``; and the FULL
  record stream is still bit-identical to the sequential loop (the
  drained round re-decides identically by per-round key parity).
- **Ops surface** — ``scan_tripwires_total{rule}``, the ``scan_tripwire``
  watchdog rule flipping /healthz, the flight-recorder bundle scoped to
  the partial block, and the /healthz ``scan`` stanza.
- **Satellites** — block-scaled /healthz staleness (no spurious 503
  mid-block), burst-vs-paced watchdog judging, the ``telemetry report``
  scan-plane lines, fleet composition (per-tenant latch, earliest-trip
  shared commit prefix).

Node counts in this file stay in the 24-31 range (prefix ``tw``) —
test_scan.py owns 16-23 — so this file's trace pins compile fresh and
cannot be satisfied by another file's cache entries.
"""

import json
import time as time_mod
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.backends.sim_device import twin_of
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.round_end import (
    METRIC_COST,
    round_end_metrics,
)
from kubernetes_rescheduling_tpu.config import (
    ControllerConfig,
    ObsConfig,
    ReconcileConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.telemetry import get_registry
from kubernetes_rescheduling_tpu.telemetry import tripwire as tw
from kubernetes_rescheduling_tpu.telemetry.registry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.server import (
    HealthState,
    OpsPlane,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import (
    RULE_SCAN_TRIPWIRE,
    SLORules,
    Watchdog,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _backend(n_nodes: int, seed: int = 0, cap_m: float = 20_000.0) -> SimBackend:
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"tw{i}" for i in range(n_nodes)],
        node_cpu_cap_m=cap_m,
        seed=seed,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    backend.inject_imbalance(backend.node_names[0])
    return backend


TIMING_FIELDS = {
    "decision_latencies_s", "decision_latency_s", "wall_s", "pipeline",
}


def _strip(rec) -> dict:
    return {k: v for k, v in rec.as_dict().items() if k not in TIMING_FIELDS}


def _events(log):
    out = []
    for r in log.records:
        if r["event"] in ("decision", "round"):
            out.append({
                k: v for k, v in r.items()
                if k not in ("ts", "decision_latency_s")
            })
    return out


def _run(
    *, scan_block: int, n_nodes: int, rounds: int, obs: ObsConfig = None,
    algo: str = "communication", seed: int = 0, backend=None,
    reconcile: ReconcileConfig = None, ops=None, with_logger: bool = True,
):
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=seed,
        controller=ControllerConfig(scan_block=scan_block),
        obs=obs if obs is not None else ObsConfig(),
        reconcile=reconcile if reconcile is not None else ReconcileConfig(),
    )
    logger = StructuredLogger(name="tw") if with_logger else None
    result = run_controller(
        backend if backend is not None else _backend(n_nodes, seed=seed),
        cfg, key=jax.random.PRNGKey(seed), logger=logger, ops=ops,
    )
    return result, logger


# ---------------- device half: the rule kernel itself --------------------


def test_tripwire_step_rule_semantics(registry):
    """Unit pins on the carry machine: cost/streak rules fire with the
    right bits, the latch freezes later bits at 0, and the recorded
    (trip_round, trip_mask) never move after the trip."""
    state, _ = twin_of(_backend(24))
    cfg = jnp.asarray([0.1, 0.0, 2.0], jnp.float32)
    carry = tw.tripwire_init(10.0, 1.0)
    # round 0: healthy — cost within 10%, first hazard sighting
    carry, bits = tw.tripwire_step(
        carry, state, jnp.asarray(10.5), jnp.asarray(1.0),
        jnp.asarray(3), cfg,
    )
    assert int(bits) == 0 and not bool(carry[0])
    # round 1: cost 12 > 1.1 * 10 AND node 3 repeats (streak 2)
    carry, bits = tw.tripwire_step(
        carry, state, jnp.asarray(12.0), jnp.asarray(1.0),
        jnp.asarray(3), cfg,
    )
    assert int(bits) == tw.TRIP_COST_REGRESSION | tw.TRIP_HAZARD_STREAK
    assert bool(carry[0]) and int(carry[1]) == 1 and int(carry[2]) == 10
    # round 2: latched — bits 0 whatever the inputs, trip record frozen
    carry, bits = tw.tripwire_step(
        carry, state, jnp.asarray(99.0), jnp.asarray(9.0),
        jnp.asarray(3), cfg,
    )
    assert int(bits) == 0
    assert int(carry[1]) == 1 and int(carry[2]) == 10
    assert tw.rules_from_mask(int(carry[2])) == (
        "cost_regression", "hazard_streak",
    )


def test_split_tripwire_roundtrip_and_guard():
    """The bundle tail strips exactly (K + 2 values) and a bundle too
    small to carry one is a loud error, not a silent mis-slice."""
    core = np.arange(7, dtype=np.float32)
    tail = np.asarray([0, 1, 0, 2.0, 8.0], np.float32)  # K=3 bits + (r, m)
    flat, report = tw.split_tripwire(
        np.concatenate([core, tail]), rounds=3
    )
    np.testing.assert_array_equal(flat, core)
    assert report.tripped and report.trip_round == 2
    assert report.rules == ("hazard_streak",)
    np.testing.assert_array_equal(report.bits, [0, 1, 0])
    with pytest.raises(ValueError):
        tw.split_tripwire(tail, rounds=3)
    with pytest.raises(ValueError):
        tw.split_fleet_tripwire(tail, rounds=3, tenants=2)


def test_tripwire_config_validation():
    cfg = RescheduleConfig(
        algorithm="communication",
        obs=ObsConfig(tripwire_cost_frac=0.2, tripwire_hazard_streak=3),
    ).validate()
    assert cfg.obs.scan_tripwires and cfg.obs.slo_scan_tripwire
    for bad in (
        dict(tripwire_cost_frac=-0.1),
        dict(tripwire_load_factor=-1.0),
        dict(tripwire_hazard_streak=-2),
    ):
        with pytest.raises(ValueError):
            ObsConfig(**bad).validate()


# ---------------- trip-free bit-identity (THE golden pin) ----------------


def test_tripfree_bit_identical_on_off_sequential(registry):
    """The golden pin: tripwires armed but silent, scanned records and
    events bit-identical to BOTH the sequential loop and the
    tripwires-compiled-out scanned run; one counted round_end transfer
    per block; one steady-state compile per tripwire variant; zero
    tripwire counters touched."""
    rounds, block = 8, 3
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    seq, seq_log = _run(scan_block=0, n_nodes=24, rounds=rounds)
    assert fam.labels(site="round_end").value == rounds
    on, on_log = _run(scan_block=block, n_nodes=24, rounds=rounds)
    # 2 full blocks (1 pull each) + 2 drained tail rounds (1 each)
    assert fam.labels(site="round_end").value == rounds + 4
    off, off_log = _run(
        scan_block=block, n_nodes=24, rounds=rounds,
        obs=ObsConfig(scan_tripwires=False),
    )
    assert fam.labels(site="round_end").value == rounds + 8
    for a, b, c in zip(seq.rounds, on.rounds, off.rounds):
        assert _strip(a) == _strip(b) == _strip(c)
    assert _events(seq_log) == _events(on_log) == _events(off_log)
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="scan_rounds").value == 2  # one per variant
    trips = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert all(trips.labels(rule=r).value == 0 for r in tw.TRIPWIRE_RULES)
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="tripwire").value == 0
    assert drains.labels(reason="tail").value == 4


def _simulate_trips(costs, hazards, *, rounds, block, cost0, frac=0.0,
                    streak_n=0):
    """Host-side twin of the scan loop's trip schedule: which blocks
    dispatch, where each trips, which rounds drain. Mirrors
    ``_scanned_loop`` (block while >= k rounds remain, +1 drained round
    after a trip, tail drained per round) and ``tripwire_step`` (f32
    compare against the block-start baseline; streak reset at block
    start)."""
    f32 = np.float32
    pos, trips, blocks = 0, [], 0
    while rounds - pos >= block:
        blocks += 1
        base = f32(cost0 if pos == 0 else costs[pos - 1])
        prev, streak, trip = None, 0, None
        for i in range(block):
            if frac > 0 and base > 0 and (
                f32(costs[pos + i]) > f32(1.0 + f32(frac)) * base
            ):
                trip = (i, tw.TRIP_COST_REGRESSION)
            name = hazards[pos + i]
            if name is None:
                prev, streak = None, 0
            else:
                streak = streak + 1 if name == prev else 1
                prev = name
                if streak_n > 0 and streak >= streak_n and trip is None:
                    trip = (i, tw.TRIP_HAZARD_STREAK)
            if trip is not None:
                break
        if trip is None:
            pos += block
        else:
            trips.append((pos + trip[0], trip[1]))
            pos += trip[0] + 1
    return trips, blocks, rounds - pos  # trips, dispatches, tail rounds


def _initial_cost(n_nodes: int, seed: int = 0) -> float:
    state, graph = twin_of(_backend(n_nodes, seed=seed))
    return float(round_end_metrics(state, graph, top_k=0)[METRIC_COST])


# ---------------- in-trace detection: the acceptance soaks ----------------


def test_cost_blowup_trips_in_trace_acceptance(registry):
    """ISSUE 16 acceptance (cost half): the random policy inflates cost;
    with a 5% regression wire the block trips IN-TRACE at exactly the
    round the host-side rule simulation predicts, commits exactly the
    pre-trip rounds, drains the tripped round under reason="tripwire" —
    and the full record stream is STILL bit-identical to the sequential
    loop (per-round key parity re-decides drained rounds identically)."""
    rounds, block, frac = 12, 4, 0.05
    seq, seq_log = _run(scan_block=0, n_nodes=25, rounds=rounds,
                        algo="random")
    costs = [r.communication_cost for r in seq.rounds]
    hazards = [r.most_hazard for r in seq.rounds]
    trips, blocks, tail = _simulate_trips(
        costs, hazards, rounds=rounds, block=block,
        cost0=_initial_cost(25), frac=frac,
    )
    assert trips, "seed must produce at least one cost trip"
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    pulls0 = fam.labels(site="round_end").value
    sc, sc_log = _run(
        scan_block=block, n_nodes=25, rounds=rounds, algo="random",
        obs=ObsConfig(tripwire_cost_frac=frac),
    )
    # the whole stream — committed scanned rounds AND drained trip
    # rounds — matches the sequential loop bit-for-bit
    assert len(sc.rounds) == rounds
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    assert _events(seq_log) == _events(sc_log)
    # one pull per dispatch + one per drained round, nothing else
    assert fam.labels(site="round_end").value - pulls0 == (
        blocks + len(trips) + tail
    )
    fam_t = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert fam_t.labels(rule="cost_regression").value == len(trips)
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="tripwire").value == len(trips)
    # the logged trip events carry the absolute round + decoded rule
    logged = [r for r in sc_log.records if r["event"] == "scan_tripwire"]
    # controller rounds are 1-based; the simulation counts from 0
    assert [(e["round"], e["rules"]) for e in logged] == [
        (rnd + 1, ["cost_regression"]) for rnd, _ in trips
    ]
    assert all(e["mask"] == tw.TRIP_COST_REGRESSION for e in logged)


def test_hazard_streak_trips_in_trace(registry):
    """The persistence rule: a most-hazard node repeating N consecutive
    rounds inside one block trips at the round the host simulation of
    the streak carry predicts; the stream stays sequential-identical."""
    rounds, block, streak_n = 10, 5, 2
    seq, _ = _run(scan_block=0, n_nodes=26, rounds=rounds)
    hazards = [r.most_hazard for r in seq.rounds]
    trips, blocks, tail = _simulate_trips(
        [r.communication_cost for r in seq.rounds], hazards,
        rounds=rounds, block=block, cost0=0.0, streak_n=streak_n,
    )
    assert trips, "seed must produce a hazard streak"
    sc, sc_log = _run(
        scan_block=block, n_nodes=26, rounds=rounds,
        obs=ObsConfig(tripwire_hazard_streak=streak_n),
    )
    assert len(sc.rounds) == rounds
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    fam_t = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert fam_t.labels(rule="hazard_streak").value == len(trips)
    logged = [r for r in sc_log.records if r["event"] == "scan_tripwire"]
    assert [(e["round"], tuple(e["rules"])) for e in logged] == [
        (rnd + 1, ("hazard_streak",)) for rnd, _ in trips  # 1-based rounds
    ]


def test_nonfinite_detection_latency_acceptance(registry, tmp_path):
    """ISSUE 16 acceptance (corruption half): a NaN injected into the
    monitor stream (admission guard off — the tripwire is the in-trace
    backstop when host-side guards cannot see device-resident state)
    trips every block at round 0. The replay commits ZERO rounds, the
    loop still makes one round of progress per block attempt (the
    drained round), and the whole ops surface reflects it: counters,
    /healthz scan stanza, the scan_tripwire watchdog rule (503), and a
    flight-recorder bundle carrying the trip bitmask."""
    rounds, block = 4, 2
    backend = _backend(27)
    real_monitor = backend.monitor

    def poisoned():
        snap = real_monitor()
        pod_cpu = np.asarray(snap.pod_cpu).copy()
        pod_cpu[int(np.flatnonzero(np.asarray(snap.pod_valid))[0])] = np.nan
        return snap.replace(pod_cpu=jnp.asarray(pod_cpu))

    backend.monitor = poisoned
    ops = OpsPlane.from_config(
        ObsConfig(flight_recorder_rounds=8),
        registry=registry,
        bundle_dir=str(tmp_path),
    )
    res, log = _run(
        scan_block=block, n_nodes=27, rounds=rounds, backend=backend,
        reconcile=ReconcileConfig(admission=False), ops=ops,
    )
    # progress guarantee: every block attempt commits 0 scanned rounds
    # and drains exactly 1 — the run still completes all its rounds.
    # Blocks dispatch while >= block rounds remain, each consuming one
    # drained round, so rounds - block + 1 attempts trip; the rest is a
    # plain tail drain.
    trips_n = rounds - block + 1
    assert len(res.rounds) == rounds
    fam_t = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert fam_t.labels(rule="non_finite").value == trips_n
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="tripwire").value == trips_n
    logged = [r for r in log.records if r["event"] == "scan_tripwire"]
    assert len(logged) == trips_n
    assert all(
        e["block_round"] == 0 and e["rules"] == ["non_finite"]
        and e["mask"] == tw.TRIP_NON_FINITE
        for e in logged
    )
    # detection latency: the trip is recorded AT the poisoned round, not
    # K rounds later — each block's trip round IS its start round
    assert [e["round"] for e in logged] == [e["block_start"] for e in logged]
    # /healthz: the scan stanza and the active watchdog rule → 503
    payload, healthy = ops.health.snapshot()
    assert not healthy
    scan = payload["scan"]
    assert scan["blocks"] == trips_n and scan["tripped_blocks"] == trips_n
    assert scan["drains"] == {"tripwire": trips_n, "tail": rounds - trips_n}
    assert scan["last_trip"]["block_round"] == 0
    assert RULE_SCAN_TRIPWIRE in ops.watchdog.active
    assert payload["slo"]["healthy"] is False
    # the flight-recorder bundle is scoped to the partial block and
    # carries the decoded trip
    bundles = sorted(tmp_path.glob("flight_*_scan_tripwire.json"))
    assert len(bundles) == trips_n
    dumped = json.loads(bundles[0].read_text())
    assert dumped["trip"]["rules"] == ["non_finite"]
    assert dumped["trip"]["mask"] == tw.TRIP_NON_FINITE
    assert dumped["trip"]["block_round"] == 0


def test_clean_block_clears_watchdog_rule(registry, tmp_path):
    """Recovery: a tripped block flips the scan_tripwire rule, the next
    clean block clears it — /healthz goes 503 and back without a
    restart."""
    ops = OpsPlane.from_config(
        ObsConfig(), registry=registry, bundle_dir=str(tmp_path)
    )
    ops.bind(algorithm="communication")  # wires health.watchdog, as a run does
    ops.observe_scan_block(
        rounds=4, trip={"round": 7, "block_round": 3, "rules": ["non_finite"]}
    )
    assert RULE_SCAN_TRIPWIRE in ops.watchdog.active
    _, healthy = ops.health.snapshot()
    assert not healthy
    ops.observe_scan_block(rounds=4, trip=None)
    assert RULE_SCAN_TRIPWIRE not in ops.watchdog.active
    _, healthy = ops.health.snapshot()
    assert healthy
    # opt-out: with the rule disabled a trip never flips health
    ops2 = OpsPlane.from_config(
        ObsConfig(slo_scan_tripwire=False), registry=registry,
        bundle_dir=str(tmp_path),
    )
    ops2.bind(algorithm="communication")
    ops2.observe_scan_block(
        rounds=4, trip={"round": 1, "block_round": 1, "rules": ["non_finite"]}
    )
    assert RULE_SCAN_TRIPWIRE not in ops2.watchdog.active


# ---------------- satellite 1: block-scaled staleness ---------------------


def test_healthz_staleness_scales_with_inflight_block(registry, monkeypatch):
    """A dispatched K-round block is K rounds of healthy silence: the
    staleness budget scales to K * max_round_age_s while the block is in
    flight (no spurious 503 — pinned), genuine hangs past the scaled
    budget still 503, and the next committed round restores the
    per-round budget."""
    health = HealthState(max_round_age_s=60.0)
    health.mark_round()
    real_mono = time_mod.monotonic
    monkeypatch.setattr(time_mod, "monotonic", lambda: real_mono() + 120.0)
    payload, healthy = health.snapshot()
    assert payload["stale"] and not healthy  # per-round budget: stale
    health.mark_block_inflight(4)  # budget now 240s
    payload, healthy = health.snapshot()
    assert not payload["stale"] and healthy, "mid-block 503 must not fire"
    monkeypatch.setattr(time_mod, "monotonic", lambda: real_mono() + 300.0)
    payload, healthy = health.snapshot()
    assert payload["stale"] and not healthy  # a genuinely hung block
    monkeypatch.setattr(time_mod, "monotonic", lambda: real_mono() + 330.0)
    health.mark_round()  # block committed: back to the per-round budget
    payload, healthy = health.snapshot()
    assert not payload["stale"] and healthy
    monkeypatch.setattr(time_mod, "monotonic", lambda: real_mono() + 420.0)
    assert not health.snapshot()[1]  # 90s > 60s: per-round budget again


# ---------------- satellite 3: burst-vs-paced watchdog judging ------------


def test_watchdog_burst_flush_matches_paced(registry):
    """A scan block flushes K records through observe_round back-to-back;
    the watchdog must return the SAME verdicts as the paced sequential
    loop — pinned on the cost-regression and reconcile rules, with wall
    time advancing between paced observations (rules judge on values and
    round indices, never inter-arrival time)."""
    def record(rnd, cost, drift=None):
        rec = types.SimpleNamespace(
            round=rnd, decision_latency_s=0.01, communication_cost=cost,
        )
        if drift is not None:
            rec.reconcile = {"drift_pods": drift}
        return rec

    stream = [
        record(1, 10.0), record(2, 9.0), record(3, 8.0),
        record(4, 20.0, drift=3), record(5, 21.0, drift=3),
    ]
    rules = dict(
        window=8, min_samples=2, cost_regression_frac=0.5,
        max_retraces=0, reconcile_max_drift_pods=1,
    )
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    paced = Watchdog(SLORules(**rules), registry=reg_a)
    real_time = time_mod.time
    for i, rec in enumerate(stream):
        # paced: seconds elapse between rounds (monkeypatch-free: the
        # watchdog never reads the clock to judge, only to timestamp)
        time_mod.time = lambda off=i: real_time() + 10.0 * off
        try:
            paced.observe_round(rec)
        finally:
            time_mod.time = real_time
    burst = Watchdog(SLORules(**rules), registry=reg_b)
    for rec in stream:  # the scan replay: K observations, zero gaps
        burst.observe_round(rec)
    assert set(paced.active) == set(burst.active) == {
        "comm_cost_regression", "reconcile_divergence",
    }
    fam = "slo_violations_total"
    for rule in ("comm_cost_regression", "reconcile_divergence"):
        assert (
            reg_a.counter(fam, labelnames=("rule",)).labels(rule=rule).value
            == reg_b.counter(fam, labelnames=("rule",)).labels(rule=rule).value
            == 1
        )
    assert paced.healthy == burst.healthy is False


# ---------------- satellite 2: report + /healthz scan surface -------------


def test_report_surfaces_scan_plane(registry, tmp_path):
    """``telemetry report`` leads the metrics dump with the scan-plane
    digest (block size, blocks, drain + tripwire breakdowns) and
    renders scan_tripwire events in the event summary."""
    from kubernetes_rescheduling_tpu.telemetry.report import summarize_file

    registry.counter("scan_blocks_total", "t").inc(3)
    registry.gauge("scan_rounds_per_dispatch", "t").set(8)
    drains = registry.counter(
        "scan_drains_total", "t", labelnames=("reason",)
    )
    drains.labels(reason="tail").inc(2)
    drains.labels(reason="tripwire").inc()
    registry.counter(
        "scan_tripwires_total", "t", labelnames=("rule",)
    ).labels(rule="cost_regression").inc()
    metrics_path = tmp_path / "metrics.jsonl"
    registry.dump_jsonl(metrics_path)
    out = summarize_file(metrics_path)
    assert "scan plane: blocks=3 block_rounds=8" in out
    assert "drains: tail×2, tripwire×1" in out
    assert "tripwires: cost_regression×1" in out

    events_path = tmp_path / "events.jsonl"
    events_path.write_text(
        json.dumps({"event": "scan_tripwire", "round": 9,
                    "rules": ["non_finite"]}) + "\n"
        + json.dumps({"event": "round", "round": 9}) + "\n"
    )
    out = summarize_file(events_path)
    assert "scan tripwires: r9 (non_finite)" in out


def test_cli_tripwire_flags_smoke(registry, capsys):
    """The CLI knobs thread into the run config: a scanned run with a
    tripwire threshold set completes, and --no-scan-tripwires runs the
    compiled-out variant."""
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    rc = cli_main([
        "reschedule", "--scan-block", "2", "--rounds", "2",
        "--scenario", "mubench", "--imbalance",
        "--tripwire-hazard-streak", "3",
    ])
    assert rc == 0
    assert len(json.loads(capsys.readouterr().out)["rounds"]) == 2
    rc = cli_main([
        "reschedule", "--scan-block", "2", "--rounds", "2",
        "--scenario", "mubench", "--imbalance", "--no-scan-tripwires",
    ])
    assert rc == 0
    assert len(json.loads(capsys.readouterr().out)["rounds"]) == 2


# ---------------- fleet composition ---------------------------------------


def _fleet_run(scan_block: int, obs: ObsConfig = None, *, rounds: int = 6,
               algo: str = "communication"):
    from kubernetes_rescheduling_tpu.backends.fleet import make_fleet
    from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
    from kubernetes_rescheduling_tpu.config import FleetConfig

    fleet = make_fleet("mubench", 4, seed=5)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=4),
        controller=ControllerConfig(scan_block=scan_block),
        obs=obs if obs is not None else ObsConfig(),
    )
    return run_fleet_controller(fleet, cfg, key=jax.random.PRNGKey(5))


def test_fleet_tripfree_bit_identical(registry):
    """Fleet golden pin: tripwires armed and silent, per-tenant streams
    bit-identical to the sequential fleet loop AND the compiled-out
    scanned fleet, one pull per block, one compile per variant."""
    seq = _fleet_run(0)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    on = _fleet_run(3)
    assert fam.labels(site="round_end").value == 2  # 6 rounds / block of 3
    off = _fleet_run(3, ObsConfig(scan_tripwires=False))
    assert fam.labels(site="round_end").value == 4
    assert seq.tenants == on.tenants == off.tenants
    for name in seq.tenants:
        a, b, c = seq.results[name], on.results[name], off.results[name]
        assert len(a.rounds) == len(b.rounds) == len(c.rounds) == 6
        for ra, rb, rc in zip(a.rounds, b.rounds, c.rounds):
            assert _strip(ra) == _strip(rb) == _strip(rc)
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_scan_rounds").value == 2
    trips = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert all(trips.labels(rule=r).value == 0 for r in tw.TRIPWIRE_RULES)


def test_fleet_trip_truncates_to_shared_prefix(registry):
    """A tripped fleet block commits the EARLIEST trip round across
    tenants (one shared prefix — max_rounds accounting holds for every
    tenant), counts the per-tenant budget-gated twin, and the full
    per-tenant streams are still bit-identical to the sequential fleet
    loop (discarded healthy-tenant rounds re-run under key parity)."""
    rounds = 6
    seq = _fleet_run(0, rounds=rounds, algo="random")
    obs = ObsConfig(tripwire_cost_frac=0.05)
    sc = _fleet_run(3, obs, rounds=rounds, algo="random")
    fam_t = registry.counter("scan_tripwires_total", labelnames=("rule",))
    n_trips = fam_t.labels(rule="cost_regression").value
    assert n_trips >= 1, "seeded random fleet must trip the cost wire"
    drains = registry.counter("scan_drains_total", labelnames=("reason",))
    assert drains.labels(reason="tripwire").value >= 1
    # per-tenant twin counted through the budget gate
    fleet_fam = registry.counter(
        "fleet_scan_tripwires_total", labelnames=("tenant",)
    )
    per_tenant = sum(
        fleet_fam.labels(tenant=name).value for name in seq.tenants
    )
    assert per_tenant == n_trips
    # every tenant still completes every round, bit-identical
    assert seq.tenants == sc.tenants
    for name in seq.tenants:
        a, b = seq.results[name], sc.results[name]
        assert len(a.rounds) == len(b.rounds) == rounds
        for ra, rb in zip(a.rounds, b.rounds):
            assert _strip(ra) == _strip(rb)


# ---------------- slow soaks ----------------------------------------------


@pytest.mark.slow  # long-horizon trip-free parity: the on/off/sequential bit-identity stays pinned fast by test_tripfree_bit_identical_on_off_sequential above — this is the 40-round redundant variant
def test_tripfree_long_soak_bit_identical(registry):
    rounds, block = 40, 8
    seq, seq_log = _run(scan_block=0, n_nodes=28, rounds=rounds)
    on, on_log = _run(scan_block=block, n_nodes=28, rounds=rounds)
    for a, b in zip(seq.rounds, on.rounds):
        assert _strip(a) == _strip(b)
    assert _events(seq_log) == _events(on_log)
    trips = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert all(trips.labels(rule=r).value == 0 for r in tw.TRIPWIRE_RULES)


@pytest.mark.slow  # repeated-trip soak: single-trip detection latency + stream identity stay pinned fast by test_cost_blowup_trips_in_trace_acceptance above — this drives many trips through one run
def test_cost_blowup_many_trips_soak(registry):
    rounds, block, frac = 24, 4, 0.02
    seq, _ = _run(scan_block=0, n_nodes=29, rounds=rounds, algo="random")
    costs = [r.communication_cost for r in seq.rounds]
    hazards = [r.most_hazard for r in seq.rounds]
    trips, _, _ = _simulate_trips(
        costs, hazards, rounds=rounds, block=block,
        cost0=_initial_cost(29), frac=frac,
    )
    assert len(trips) >= 2
    sc, _ = _run(
        scan_block=block, n_nodes=29, rounds=rounds, algo="random",
        obs=ObsConfig(tripwire_cost_frac=frac),
    )
    for a, b in zip(seq.rounds, sc.rounds):
        assert _strip(a) == _strip(b)
    fam_t = registry.counter("scan_tripwires_total", labelnames=("rule",))
    assert fam_t.labels(rule="cost_regression").value == len(trips)
