"""Recorded-wire fixtures for the k8s adapter.

The fixtures in ``tests/fixtures/k8s_wire/`` are full API-server response
bodies (schema-faithful to what a kind cluster's apiserver + metrics-server
emit: resourceVersions, ownerReference chains, conditions, allocatable vs
capacity, metrics timestamps/windows) rather than the minimal hand-rolled
dicts of ``test_backends.FakeCluster`` — so the adapter's parsing is
exercised against realistic wire shapes, including the real-world
oddities:

- a control-plane node with its taint (must be excluded from placement),
- a pod metrics row MISSING for a just-(re)started pod (metrics lag),
- a node metrics row missing entirely (rebooted node),
- a multi-container pod (sidecar) whose usage must be container-summed,
- a Pending pod with no nodeName,
- a DaemonSet-owned pod that maps to no tracked Deployment,
- a mid-delete 404 flap (deletion-in-progress read succeeds, then 404),
- a stored Deployment carrying stale placement pins + NotIn affinity from
  a previous move, which re-create must strip.

Reference parity: podmonitor.py:7-125 (snapshot), get_resource_usage.py
(container-summed usage), delete_replaced_pod.py:8-22 (delete poll).
"""

import copy
import json
from pathlib import Path

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.base import MoveRequest
from kubernetes_rescheduling_tpu.backends.k8s import K8sBackend
from kubernetes_rescheduling_tpu.core.state import UNASSIGNED
from kubernetes_rescheduling_tpu.core.workmodel import ServiceSpec, Workmodel

FIXTURES = Path(__file__).parent / "fixtures" / "k8s_wire"


def load(name):
    return json.loads((FIXTURES / name).read_text())


class ApiError(Exception):
    def __init__(self, status):
        self.status = status


class WireReplayCluster:
    """Serves the recorded response bodies verbatim; deployment reads
    follow a scripted per-name sequence so delete/create flows can replay
    real flaps (deletion-in-progress read → 404 → recreated)."""

    def __init__(self):
        self.node_list = load("node_list.json")
        self.pod_list = load("pod_list.json")
        self.node_metrics = load("node_metrics.json")
        self.pod_metrics = load("pod_metrics.json")
        self.deployments = {"reviews": load("deployment_reviews.json")}
        # name -> list of scripted responses for read_namespaced_deployment
        # (each entry a body dict, or an int HTTP status to raise)
        self.read_script: dict[str, list] = {}
        self.deleted: list[str] = []
        self.created: list[dict] = []
        self.patched_nodes: list[tuple[str, dict]] = []

    # CoreV1
    def list_node(self, watch=False):
        return self.node_list

    def list_namespaced_pod(self, namespace, watch=False):
        items = [
            p for p in self.pod_list["items"]
            if p["metadata"]["namespace"] == namespace
        ]
        return {"kind": "PodList", "apiVersion": "v1", "items": items}

    def list_pod_for_all_namespaces(self, watch=False):
        return self.pod_list

    def patch_node(self, name, body):
        self.patched_nodes.append((name, body))

    # AppsV1
    def read_namespaced_replica_set(self, name, namespace):
        # RS name is <deployment>-<hash>; real RS bodies carry the
        # Deployment ownerReference
        dep = name.rsplit("-", 1)[0]
        return {
            "metadata": {
                "name": name,
                "namespace": namespace,
                "ownerReferences": [
                    {"apiVersion": "apps/v1", "kind": "Deployment",
                     "name": dep, "controller": True}
                ],
            }
        }

    def read_namespaced_deployment(self, name, namespace):
        script = self.read_script.get(name)
        if script:
            entry = script.pop(0)
            if isinstance(entry, int):
                raise ApiError(entry)
            return entry
        if name not in self.deployments:
            raise ApiError(404)
        return self.deployments[name]

    def delete_namespaced_deployment(self, name, namespace, body=None):
        self.deleted.append(name)
        self.deployments.pop(name, None)

    def create_namespaced_deployment(self, namespace, body):
        self.created.append(body)
        self.deployments[body["metadata"]["name"]] = body

    # CustomObjects
    def list_cluster_custom_object(self, group, version, plural):
        assert (group, version, plural) == ("metrics.k8s.io", "v1beta1", "nodes")
        return self.node_metrics

    def list_namespaced_custom_object(self, group, version, namespace, plural):
        assert (group, version, plural) == ("metrics.k8s.io", "v1beta1", "pods")
        return self.pod_metrics


def bookinfo_wm():
    return Workmodel(
        services=(
            ServiceSpec(name="productpage", callees=("details", "reviews")),
            ServiceSpec(name="details"),
            ServiceSpec(name="reviews", callees=("ratings",), replicas=2),
            ServiceSpec(name="ratings"),
        ),
        source="bookinfo-wire",
    )


@pytest.fixture
def wire_backend():
    fc = WireReplayCluster()
    backend = K8sBackend(
        workmodel=bookinfo_wm(),
        namespace="default",
        core_api=fc,
        apps_api=fc,
        custom_api=fc,
        control_plane_names=("kind-control-plane",),
        sleeper=lambda s: None,
        delete_timeout_s=5.0,
        delete_poll_interval_s=1.0,
    )
    return backend, fc


class TestWireMonitor:
    def test_control_plane_excluded(self, wire_backend):
        backend, _ = wire_backend
        assert backend.node_names == ["worker1", "worker2", "worker3"]

    def test_snapshot_parses_wire_bodies(self, wire_backend):
        backend, _ = wire_backend
        st = backend.monitor()
        names = list(st.pod_names)
        # DaemonSet pod is not tracked
        assert all("node-exporter" not in n for n in names)
        # capacities from the wire body: 20 CPUs = 20000 millicores
        assert float(st.node_cpu_cap[0]) == 20000.0
        # sidecar usage container-summed: 142311209n + 31250000n → 142m +
        # 31m = 173m (integer millicores per container — reference
        # unit_convertion semantics)
        i = names.index("productpage-7d9c56b8f4-abcde")
        assert float(st.pod_cpu[i]) == 173.0
        # missing pod-metrics row (ratings) tolerated → usage 0
        j = names.index("ratings-6cf8d8c9b5-q4r7s")
        assert float(st.pod_cpu[j]) == 0.0
        assert bool(st.pod_valid[j])
        # pending pod has no node
        k = names.index("reviews-5b8cd9fd6c-zx81v")
        assert int(st.pod_node[k]) == UNASSIGNED

    def test_base_load_from_node_metrics_with_missing_row(self, wire_backend):
        backend, _ = wire_backend
        st = backend.monitor()
        # worker1 base = node usage (1824516789n → 1824m) − tracked pod
        # usage on it (productpage 173m + details 88m)
        assert float(st.node_base_cpu[0]) == pytest.approx(
            1824.0 - (173.0 + 88.0), rel=1e-3
        )
        # worker3's metrics row is missing → base clamps to 0
        assert float(st.node_base_cpu[2]) == 0.0

    def test_restart_counts_summed_across_containers(self, wire_backend):
        backend, _ = wire_backend
        counts = backend.pod_restart_counts()
        # reviews pod restarted twice; productpage's sidecar once
        assert counts["reviews-5b8cd9fd6c-k9m2p"] == 2
        assert counts["productpage-7d9c56b8f4-abcde"] == 1


class TestWireMove:
    def test_apply_move_with_mid_delete_404_flap(self, wire_backend):
        backend, fc = wire_backend
        dep = fc.deployments["reviews"]
        ready = copy.deepcopy(dep)
        ready["status"]["readyReplicas"] = 2
        deleting = copy.deepcopy(dep)
        deleting["metadata"]["deletionTimestamp"] = "2026-07-29T16:05:00Z"
        # script: initial read (for the spec) → deletion-in-progress read
        # (the flap: object still served after delete accepted) → 404 →
        # recreated-but-not-ready → ready
        not_ready = copy.deepcopy(dep)
        not_ready["status"]["readyReplicas"] = 0
        fc.read_script["reviews"] = [dep, deleting, 404, not_ready, ready]
        landed = backend.apply_move(
            MoveRequest(
                service="reviews",
                target_node="worker3",
                mechanism="nodeSelector",
            )
        )
        assert landed == "worker3"
        assert fc.deleted == ["reviews"]
        assert len(fc.created) == 1

    def test_recreate_strips_stale_pins_and_server_fields(self, wire_backend):
        backend, fc = wire_backend
        fc.read_script["reviews"] = [fc.deployments["reviews"], 404]
        backend.apply_move(
            MoveRequest(
                service="reviews",
                target_node="worker1",
                mechanism="nodeSelector",
            )
        )
        body = fc.created[0]
        tmpl = body["spec"]["template"]["spec"]
        # stale placement from the fixture is gone; only the new pin remains
        assert "nodeName" not in tmpl
        assert tmpl.get("nodeSelector") == {"kubernetes.io/hostname": "worker1"}
        aff = json.dumps(tmpl.get("affinity") or {})
        assert "NotIn" not in aff  # previous move's exclusion stripped
        # server-populated metadata is not replayed into the create
        md = body["metadata"]
        assert "resourceVersion" not in md and "uid" not in md
        assert "status" not in body
        # the workload spec survives (env, resources, ports); probes are
        # deliberately dropped — the re-create body is the reference's
        # minimal redeployable spec (delete_replaced_pod.py:64-142)
        c = tmpl["containers"][0]
        assert c["resources"]["requests"]["cpu"] == "100m"
        assert c["env"] == [{"name": "LOG_DIR", "value": "/tmp/logs"}]
        assert c["ports"][0]["containerPort"] == 9080
        assert "livenessProbe" not in c and "readinessProbe" not in c

    def test_delete_flap_exhausting_poll_budget_fails_closed(self, wire_backend):
        backend, fc = wire_backend
        dep = fc.deployments["reviews"]
        # the object never 404s within the poll budget (stuck finalizer)
        fc.read_script["reviews"] = [dep] + [dep] * 50
        landed = backend.apply_move(
            MoveRequest(
                service="reviews",
                target_node="worker3",
                mechanism="nodeSelector",
            )
        )
        assert landed is None  # move reported failed, controller continues


class RvReplayCluster(WireReplayCluster):
    """WireReplayCluster whose namespaced pod listing carries the list
    resourceVersion (the real apiserver always does; the base fake
    rebuilds a bare dict) and which counts owner-chain walks."""

    def __init__(self):
        super().__init__()
        self.rs_reads = 0

    def list_namespaced_pod(self, namespace, watch=False):
        out = super().list_namespaced_pod(namespace, watch)
        out["metadata"] = {
            "resourceVersion": self.pod_list["metadata"]["resourceVersion"]
        }
        return out

    def read_namespaced_replica_set(self, name, namespace):
        self.rs_reads += 1
        return super().read_namespaced_replica_set(name, namespace)


class TestMonitorShortCircuit:
    def _backend(self):
        fc = RvReplayCluster()
        return (
            K8sBackend(
                workmodel=bookinfo_wm(),
                namespace="default",
                core_api=fc,
                apps_api=fc,
                custom_api=fc,
                control_plane_names=("kind-control-plane",),
                sleeper=lambda s: None,
            ),
            fc,
        )

    def test_unchanged_resource_versions_skip_the_rebuild(self):
        backend, fc = self._backend()
        st1 = backend.monitor()
        walks = fc.rs_reads
        assert walks > 0
        st2 = backend.monitor()
        # structure reused: zero additional owner-chain walks, same
        # parsed snapshot content (usage metrics re-fetched — here the
        # fake serves identical metrics, so the states are bit-equal)
        assert fc.rs_reads == walks
        np.testing.assert_array_equal(
            np.asarray(st2.pod_node), np.asarray(st1.pod_node)
        )
        np.testing.assert_array_equal(
            np.asarray(st2.pod_cpu), np.asarray(st1.pod_cpu)
        )

    def test_changed_pod_list_rebuilds_but_owner_walks_stay_cached(self):
        backend, fc = self._backend()
        backend.monitor()
        walks = fc.rs_reads
        # the list RV churns (on a real apiserver it tracks the
        # cluster-global storage revision, so this is the COMMON case):
        # the structure re-parses, but each known pod's owner chain is
        # immutable for its lifetime — no re-walks
        fc.pod_list["metadata"]["resourceVersion"] = "99999"
        backend.monitor()
        assert backend._struct_memo[0][1] == "99999"  # rebuilt
        assert fc.rs_reads == walks  # per-pod owner memo held
        # a NEW pod name walks once; a DELETED pod's entry is pruned
        new_pod = copy.deepcopy(fc.pod_list["items"][0])
        new_pod["metadata"]["name"] = "reviews-5b8cd9fd6c-fresh"
        fc.pod_list["items"].append(new_pod)
        fc.pod_list["metadata"]["resourceVersion"] = "99999"  # same rv:
        backend.monitor()  # short-circuit — new pod invisible until rv moves
        assert fc.rs_reads == walks
        fc.pod_list["metadata"]["resourceVersion"] = "100001"
        backend.monitor()
        assert fc.rs_reads == walks + 1  # exactly the new pod's walk
        assert "reviews-5b8cd9fd6c-fresh" in backend._owner_memo

    def test_missing_resource_version_never_short_circuits(self, wire_backend):
        backend, fc = wire_backend  # base fake: no list rv on pods
        backend.monitor()
        backend.monitor()
        assert backend._struct_memo is None
