"""The live ops plane (ISSUE 3): /metrics /healthz /events endpoint,
decision explainability, flight recorder, SLO watchdog — plus the
Prometheus exposition conformance pin and the live chaos-soak
acceptance test at the bottom."""

import json
import math
import os
import re
import signal
import types
import urllib.error
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.bench.boundary import CircuitBreaker
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.harness import make_backend, run_chaos_soak
from kubernetes_rescheduling_tpu.config import ObsConfig, RescheduleConfig
from kubernetes_rescheduling_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    OpsPlane,
    OpsServer,
    SLORules,
    Watchdog,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.explain import (
    check_decisions,
    explanation_consistent,
    greedy_explanation,
    iter_decisions,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _get(port, path):
    """(status, body bytes) without raising on non-200."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------- Prometheus exposition conformance ----------------


_SAMPLE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s(\S+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v):
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def parse_exposition(text):
    """Minimal strict parser for text format 0.0.4: returns
    (families: name -> {type, help}, samples: (name, labels-frozenset) ->
    float). Raises on malformed lines or duplicate samples."""
    families = {}
    samples = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, help_text = line[len("# HELP "):].split(" ", 1)
            families.setdefault(name, {})["help"] = help_text
        elif line.startswith("# TYPE "):
            name, kind = line[len("# TYPE "):].split(" ", 1)
            families.setdefault(name, {})["type"] = kind
        else:
            m = _SAMPLE.match(line)
            assert m, f"malformed sample line: {line!r}"
            name, labelstr, value = m.groups()
            labels = {}
            if labelstr:
                consumed = 0
                for lm in _LABEL.finditer(labelstr):
                    labels[lm.group(1)] = _unescape(lm.group(2))
                    consumed += len(lm.group(0))
                stripped = re.sub(r"[,\s]", "", labelstr)
                joined = re.sub(
                    r"[,\s]", "", "".join(
                        f'{k}="{v}"' for k, v in
                        ((lm.group(1), lm.group(2)) for lm in _LABEL.finditer(labelstr))
                    )
                )
                assert stripped == joined, f"unparsed label text in {line!r}"
            v = float("inf") if value == "+Inf" else float(value)
            key = (name, frozenset(labels.items()))
            assert key not in samples, f"duplicate sample {line!r}"
            samples[key] = v
    return families, samples


def assert_exposition_conformant(text):
    """The wire-format invariants the /metrics endpoint must keep."""
    families, samples = parse_exposition(text)
    by_family = {}
    for (name, labels), v in samples.items():
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = base if base in families else name
        by_family.setdefault(fam, []).append((name, dict(labels), v))
    for name, meta in families.items():
        assert "type" in meta, f"{name}: TYPE line missing"
        rows = by_family.get(name, [])
        assert rows, f"{name}: family declared but no samples"
        if meta["type"] == "histogram":
            series = {}
            for sample_name, labels, v in rows:
                key = frozenset(
                    (k, lv) for k, lv in labels.items() if k != "le"
                )
                series.setdefault(key, {"buckets": [], "sum": None, "count": None})
                if sample_name.endswith("_bucket"):
                    series[key]["buckets"].append((float(labels["le"]), v))
                elif sample_name.endswith("_sum"):
                    series[key]["sum"] = v
                elif sample_name.endswith("_count"):
                    series[key]["count"] = v
                else:
                    raise AssertionError(f"stray histogram sample {sample_name}")
            for key, s in series.items():
                assert s["sum"] is not None and s["count"] is not None
                buckets = sorted(s["buckets"])
                assert buckets, f"{name}: histogram with no buckets"
                assert buckets[-1][0] == math.inf, f"{name}: +Inf bucket missing"
                counts = [c for _, c in buckets]
                assert counts == sorted(counts), f"{name}: buckets not cumulative"
                assert buckets[-1][1] == s["count"], (
                    f"{name}: +Inf bucket != count"
                )
    return families, samples


def test_exposition_conformance_generated(registry):
    """Everything the registry can emit — labeled counters (with chars
    needing escaping), gauges, histograms — parses and keeps the
    histogram invariants."""
    registry.counter("a_total", "As", labelnames=("k",)).labels(
        k='we"ird\\lab\nel'
    ).inc(2)
    registry.gauge("g", "G").set(-1.5)
    h = registry.histogram("h_seconds", "H", labelnames=("x",), buckets=(0.1, 1.0))
    for v, x in ((0.05, "a"), (0.5, "a"), (99.0, "a"), (0.2, "b")):
        h.labels(x=x).observe(v)
    families, samples = assert_exposition_conformant(registry.expose())
    assert families["a_total"]["type"] == "counter"
    assert families["h_seconds"]["type"] == "histogram"
    assert samples[("a_total", frozenset([("k", 'we"ird\\lab\nel')]))] == 2
    assert samples[("g", frozenset())] == -1.5


def test_exposition_golden_file(registry):
    """Byte-exact pin of the wire format a scraper sees. Regenerate with
    tests/fixtures/make_exposition_golden.py if the format deliberately
    changes."""
    golden = Path(__file__).parent / "fixtures" / "exposition_golden.prom"
    registry.counter(
        "rounds_total", "rescheduling rounds executed", labelnames=("algorithm",)
    ).labels(algorithm="communication").inc(3)
    registry.gauge("communication_cost", "cost", labelnames=("algorithm",)).labels(
        algorithm="communication"
    ).set(12.5)
    h = registry.histogram(
        "decision_seconds", "latency", labelnames=("algorithm",),
        buckets=(0.001, 0.01, 0.1),
    ).labels(algorithm="communication")
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    registry.counter("esc_total", "label escaping", labelnames=("p",)).labels(
        p='a"b\\c\nd'
    ).inc()
    # the attribution plane's topology families render through the same
    # path (exercised via the real publisher, not hand-set gauges)
    from kubernetes_rescheduling_tpu.telemetry.attribution import (
        publish_attribution,
    )

    publish_attribution(
        registry,
        {
            "total": 10.0,
            "tail": 1.0,
            "edges": [
                {"src_service": "a", "dst_service": "b", "src_node": "n0",
                 "dst_node": "n1", "cost": 6.0},
            ],
            "node_pairs": [["n0", "n1", 12.0], ["n1", "n0", 12.0]],
            "ingress": {"n0": 5.0, "n1": 5.0},
            "egress": {"n0": 5.0, "n1": 5.0},
        },
        top_k=2,
    )
    # the fleet-rollup families render through the same real publisher
    # (keep the matrix IDENTICAL to make_exposition_golden.py's)
    from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
        decode_rollup,
        publish_rollup,
        rollup_numpy,
    )

    matrix = [
        [10.0, 1.0, 0.0, 0.0, 0.0],
        [40.0, 4.0, 1.0, 0.0, 2.0],
        [20.0, 2.0, 0.0, 0.0, 0.0],
        [30.0, 3.0, 0.0, 1.0, 1.0],
    ]
    publish_rollup(
        registry,
        decode_rollup(rollup_numpy(matrix, top_k=2), top_k=2),
    )
    # the serving plane's documented micro-bucket preset renders through
    # the same histogram path (MICRO_BUCKETS, 50µs–250ms — the preset
    # every serving_request_seconds{stage} family selects at
    # registration); samples straddle below/inside/above the preset
    from kubernetes_rescheduling_tpu.telemetry.registry import MICRO_BUCKETS

    sr = registry.histogram(
        "serving_request_seconds",
        "per-request serving latency by stage",
        labelnames=("stage",),
        buckets=MICRO_BUCKETS,
    )
    for v, stage in (
        (20e-6, "total"), (300e-6, "total"), (0.004, "total"),
        (0.5, "total"), (120e-6, "queue_wait"),
    ):
        sr.labels(stage=stage).observe(v)
    # the SLO v2 families render through the real history plane + budget
    # engine (keep the feed IDENTICAL to make_exposition_golden.py's):
    # a 2-series budget store, 4 ticks of synthetic counters, one
    # evaluation, then a third family forcing exactly one counted LRU
    # eviction
    from kubernetes_rescheduling_tpu.telemetry.slo import SloEngine, SloSpec
    from kubernetes_rescheduling_tpu.telemetry.timeseries import SeriesStore

    store = SeriesStore(
        capacity=8, max_series=2, registry=registry,
        families=("ok_total", "bad_total", "spill_total"),
    )
    for tick, (ok, bad) in enumerate(
        ((10, 0), (20, 1), (30, 3), (40, 6)), start=1
    ):
        store.sample(
            [
                {"metric": "ok_total", "type": "counter", "labels": {},
                 "value": float(ok)},
                {"metric": "bad_total", "type": "counter", "labels": {},
                 "value": float(bad)},
            ],
            tick,
        )
    engine = SloEngine(
        (SloSpec(name="golden", objective=0.9,
                 good=(("ok_total", ()),), bad=(("bad_total", ()),)),),
        store, registry=registry,
        budget_window=8, fast_window=4, fast_burn=2.0,
        slow_window=6, slow_burn=1.5,
    )
    engine.evaluate(4)
    store.sample(
        [{"metric": "spill_total", "type": "counter", "labels": {},
          "value": 1.0}],
        5,
    )
    assert registry.expose() == golden.read_text()


def test_exposition_conformance_attribution_families(registry):
    """Strict-parser pass over the attribution metric families as a
    LIVE controller emits them (multi-round, stale pairs zeroed)."""
    from kubernetes_rescheduling_tpu.telemetry.attribution import (
        publish_attribution,
    )

    for rnd in range(3):
        publish_attribution(
            registry,
            {
                "total": 10.0 + rnd,
                "tail": 0.0,
                "edges": [
                    {"src_service": "a", "dst_service": "b",
                     "src_node": f"n{rnd % 2}", "dst_node": "n2",
                     "cost": 10.0 + rnd},
                ],
                "node_pairs": [
                    [f"n{rnd % 2}", "n2", 2 * (10.0 + rnd)],
                    ["n2", f"n{rnd % 2}", 2 * (10.0 + rnd)],
                ],
                "ingress": {"n0": 5.0, "n1": 0.0, "n2": 5.0 + rnd},
                "egress": {"n0": 5.0, "n1": 0.0, "n2": 5.0 + rnd},
            },
            top_k=3,
        )
    families, samples = assert_exposition_conformant(registry.expose())
    for name in (
        "comm_cost_node_pair",
        "comm_cost_node_ingress",
        "comm_cost_node_egress",
        "comm_cost_edge_topk",
    ):
        assert families[name]["type"] == "gauge"
    # rank labels are the fixed budget; the alternating node pair from
    # round 1 is still exposed but zeroed
    assert samples[("comm_cost_edge_topk", frozenset([("rank", "0")]))] == 12.0
    assert samples[
        ("comm_cost_node_pair", frozenset([("src", "n1"), ("dst", "n2")]))
    ] == 0.0


def test_exposition_conformance_slo_families(registry):
    """Strict-parser pass over the SLO v2 families as a LIVE engine
    emits them: budget/burn gauges every tick, the store's bound
    gauge/eviction counter once the series budget trips."""
    from kubernetes_rescheduling_tpu.telemetry.slo import SloEngine, SloSpec
    from kubernetes_rescheduling_tpu.telemetry.timeseries import SeriesStore

    store = SeriesStore(capacity=4, max_series=2, registry=registry,
                        families=None)
    engine = SloEngine(
        (SloSpec(name="avail", objective=0.95,
                 good=(("ok_total", ()),), bad=(("bad_total", ()),)),),
        store, registry=registry,
        budget_window=8, fast_window=4, slow_window=6,
    )
    for tick in range(1, 6):
        store.sample(
            [
                {"metric": "ok_total", "type": "counter", "labels": {},
                 "value": 10.0 * tick},
                {"metric": "bad_total", "type": "counter", "labels": {},
                 "value": 1.0 * tick},
            ],
            tick,
        )
        engine.evaluate(tick)
    # a third family past max_series=2: eviction counted, bound holds
    store.sample(
        [{"metric": "spill_total", "type": "counter", "labels": {},
          "value": 1.0}],
        6,
    )
    families, samples = assert_exposition_conformant(registry.expose())
    assert families["slo_budget_remaining_frac"]["type"] == "gauge"
    assert families["slo_burn_rate"]["type"] == "gauge"
    assert families["timeseries_series"]["type"] == "gauge"
    assert families["timeseries_evictions_total"]["type"] == "counter"
    assert samples[("timeseries_series", frozenset())] == 2.0
    assert samples[("timeseries_evictions_total", frozenset())] == 1.0
    assert (
        ("slo_burn_rate", frozenset([("slo", "avail"), ("window", "fast")]))
        in samples
    )


# ---------------- ops server ----------------


class TestOpsServer:
    def test_metrics_endpoint_serves_live_registry(self, registry):
        registry.counter("x_total", "x").inc(7)
        srv = OpsServer(port=0, registry=registry)
        port = srv.start()
        try:
            status, body = _get(port, "/metrics")
            assert status == 200
            assert "x_total 7" in body.decode()
            # LIVE: a later increment shows up on the next scrape
            registry.counter("x_total").inc()
            _, body2 = _get(port, "/metrics")
            assert "x_total 8" in body2.decode()
            assert_exposition_conformant(body2.decode())
        finally:
            srv.stop()

    def test_healthz_follows_breaker_state(self, registry):
        from kubernetes_rescheduling_tpu.telemetry.server import HealthState

        health = HealthState()
        breaker = CircuitBreaker(max_consecutive_failures=1, registry=registry)
        health.breaker = breaker
        srv = OpsServer(port=0, registry=registry, health=health)
        port = srv.start()
        try:
            status, body = _get(port, "/healthz")
            assert status == 200 and json.loads(body)["status"] == "ok"
            breaker.record_failure()  # opens at 1
            status, body = _get(port, "/healthz")
            payload = json.loads(body)
            assert status == 503
            assert payload["status"] == "unhealthy"
            assert payload["breaker"] == "open"
            breaker.record_success()  # re-closes
            status, _ = _get(port, "/healthz")
            assert status == 200
        finally:
            srv.stop()

    def test_events_endpoint_serves_logger_tail(self, registry):
        logger = StructuredLogger(name="t")
        for i in range(10):
            logger.info("tick", i=i)
        srv = OpsServer(
            port=0, registry=registry, events_source=lambda: logger.records
        )
        port = srv.start()
        try:
            status, body = _get(port, "/events?n=3")
            assert status == 200
            events = json.loads(body)
            assert [e["i"] for e in events] == [7, 8, 9]
            status, _ = _get(port, "/nope")
            assert status == 404
        finally:
            srv.stop()

    def test_events_tail_limit_bounds_and_defaults(self, registry):
        """`?n=` tail-limits the response; DEFAULT is the full (bounded)
        ring; n is clamped, order is oldest→newest, content type JSON."""
        import urllib.request

        logger = StructuredLogger(name="t", max_records=16)
        for i in range(20):
            logger.info("tick", i=i)
        srv = OpsServer(
            port=0, registry=registry, events_source=lambda: logger.records
        )
        port = srv.start()
        try:
            # default: the full ring (itself bounded at max_records)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/events", timeout=10
            ) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"] == "application/json"
                events = json.loads(resp.read())
            assert [e["i"] for e in events] == list(range(4, 20))
            # tail limit: the NEWEST n, oldest->newest within the tail
            _, body = _get(port, "/events?n=2")
            assert [e["i"] for e in json.loads(body)] == [18, 19]
            # clamped: n beyond the ring returns the whole ring, not 500
            _, body = _get(port, "/events?n=9999")
            assert len(json.loads(body)) == 16
            # n=0 and junk are bounded too
            _, body = _get(port, "/events?n=0")
            assert json.loads(body) == []
            _, body = _get(port, "/events?n=bogus")
            assert len(json.loads(body)) == 16
        finally:
            srv.stop()

    def test_healthz_round_age_survives_wall_clock_step(
        self, registry, monkeypatch
    ):
        """An NTP wall-clock step must not fake staleness (or mask it):
        the round age computes from the MONOTONIC clock; wall time is
        display-only."""
        import time as time_mod

        from kubernetes_rescheduling_tpu.telemetry.server import HealthState

        health = HealthState(max_round_age_s=60.0)
        health.mark_round()
        payload, healthy = health.snapshot()
        assert healthy and payload["last_round_age_s"] < 1.0

        real_time = time_mod.time
        # wall clock jumps A DAY forward (NTP step): age must not move
        monkeypatch.setattr(time_mod, "time", lambda: real_time() + 86400.0)
        payload, healthy = health.snapshot()
        assert healthy, "wall-clock step must not force a spurious 503"
        assert payload["last_round_age_s"] < 1.0
        assert not payload["stale"]

        # genuine staleness is still caught: the MONOTONIC clock advances
        real_mono = time_mod.monotonic
        monkeypatch.setattr(
            time_mod, "monotonic", lambda: real_mono() + 120.0
        )
        payload, healthy = health.snapshot()
        assert payload["stale"] and not healthy
        # and the server surfaces it as a 503
        srv = OpsServer(port=0, registry=registry, health=health)
        port = srv.start()
        try:
            status, body = _get(port, "/healthz")
            assert status == 503
            assert json.loads(body)["stale"] is True
        finally:
            srv.stop()

    def test_requests_are_counted_not_printed(self, registry):
        srv = OpsServer(port=0, registry=registry)
        port = srv.start()
        try:
            _get(port, "/healthz")
            _get(port, "/healthz")
            fam = registry.counter(
                "ops_http_requests_total", labelnames=("endpoint",)
            )
            assert fam.labels(endpoint="/healthz").value == 2
        finally:
            srv.stop()


# ---------------- SLO watchdog ----------------


def _rec(lat=0.01, cost=10.0):
    return types.SimpleNamespace(decision_latency_s=lat, communication_cost=cost)


class TestWatchdog:
    def test_latency_p95_rule_fires_and_recovers(self, registry):
        logger = StructuredLogger(name="t")
        wd = Watchdog(
            SLORules(window=8, min_samples=4, latency_p95_s=0.1,
                     max_retraces=0),
            registry=registry, logger=logger,
        )
        for _ in range(4):
            assert wd.observe_round(_rec(lat=0.01)) == []
        raised = []
        for _ in range(6):
            raised += wd.observe_round(_rec(lat=1.0))
        assert any(v["rule"] == "round_latency_p95" for v in raised)
        assert not wd.healthy
        fam = registry.counter("slo_violations_total", labelnames=("rule",))
        assert fam.labels(rule="round_latency_p95").value == 1  # entry, not per-round
        # recovery: fast rounds push p95 back under
        for _ in range(8):
            wd.observe_round(_rec(lat=0.001))
        assert wd.healthy
        events = [r["event"] for r in logger.records]
        assert "slo_violation" in events and "slo_recovered" in events

    def test_cost_regression_rule(self, registry):
        wd = Watchdog(
            SLORules(window=10, min_samples=3, cost_regression_frac=0.5,
                     max_retraces=0),
            registry=registry,
        )
        for c in (10.0, 9.0, 8.0):
            wd.observe_round(_rec(cost=c))
        assert wd.healthy
        wd.observe_round(_rec(cost=20.0))  # > 1.5x the window min (8.0)
        assert not wd.healthy
        assert "comm_cost_regression" in wd.active

    def test_retrace_rule_reads_registry(self, registry):
        wd = Watchdog(SLORules(max_retraces=1), registry=registry)
        fam = registry.counter("jax_traces_total", "t", labelnames=("fn",))
        fam.labels(fn="hot").inc()  # steady state: exactly 1
        wd.observe_round(_rec())
        assert wd.healthy
        fam.labels(fn="hot").inc()  # a retrace
        wd.observe_round(_rec())
        assert not wd.healthy
        assert wd.active["retrace"]["fns"] == {"hot": 2}

    def test_cost_rule_min_samples_one_does_not_crash(self, registry):
        """min_samples=1 is valid config; the regression baseline needs a
        second sample, so the first round must simply not judge."""
        wd = Watchdog(
            SLORules(min_samples=1, cost_regression_frac=0.5, max_retraces=0),
            registry=registry,
        )
        assert wd.observe_round(_rec(cost=10.0)) == []  # no min([]) crash
        wd.observe_round(_rec(cost=20.0))
        assert "comm_cost_regression" in wd.active

    def test_rebase_starts_fresh_window(self, registry):
        """A new run binding rebases: another cell's shape compiling once
        is not a retrace, and the previous cell's cost scale is not a
        regression baseline."""
        wd = Watchdog(
            SLORules(min_samples=2, cost_regression_frac=0.5, max_retraces=1),
            registry=registry,
        )
        fam = registry.counter("jax_traces_total", "t", labelnames=("fn",))
        fam.labels(fn="decide").inc()
        wd.observe_round(_rec(cost=1.0))
        assert wd.healthy
        wd.rebase()  # next cell binds
        fam.labels(fn="decide").inc()  # NEW SHAPE compiles once
        # cost jumps because the new cell's scenario is bigger — not a
        # regression, the old window was cleared
        wd.observe_round(_rec(cost=100.0))
        assert wd.healthy, wd.active
        # but a real retrace within the new window still flags
        fam.labels(fn="decide").inc()
        wd.observe_round(_rec(cost=100.0))
        assert "retrace" in wd.active

    def test_rules_validate(self):
        with pytest.raises(ValueError):
            SLORules(window=1).validate()
        with pytest.raises(ValueError):
            SLORules(latency_p95_s=-1).validate()


# ---------------- flight recorder ----------------


class TestFlightRecorder:
    def test_ring_is_bounded_and_dump_self_contained(self, tmp_path, registry):
        fr = FlightRecorder(capacity=3, bundle_dir=tmp_path, registry=registry)
        for r in range(1, 6):
            fr.record_round(round=r, digest=f"d{r}", record={"round": r})
        assert [e["round"] for e in fr.rounds] == [3, 4, 5]  # newest 3
        registry.counter("x_total", "x").inc()
        p = fr.dump("breaker_open", transition={"to": "open"})
        bundle = json.loads(p.read_text())
        assert bundle["kind"] == "flight_recorder_bundle"
        assert bundle["reason"] == "breaker_open"
        assert len(bundle["rounds"]) == 3
        assert any(m["metric"] == "x_total" for m in bundle["metrics"])
        assert bundle["manifest"]["python"]  # provenance rode along
        fam = registry.counter(
            "flight_recorder_dumps_total", labelnames=("reason",)
        )
        assert fam.labels(reason="breaker_open").value == 1

    def test_dump_is_best_effort_never_raises(self, registry):
        logger = StructuredLogger(name="t")
        fr = FlightRecorder(
            capacity=2, bundle_dir="/proc/definitely/not/writable",
            registry=registry, logger=logger,
        )
        fr.record_round(round=1)
        assert fr.dump("crash") is None  # swallowed, logged
        assert any(
            r["event"] == "flight_dump_failed" for r in logger.records
        )

    def test_no_bundle_dir_means_no_dump(self, registry):
        fr = FlightRecorder(capacity=2, registry=registry)
        assert fr.dump("crash") is None

    def test_sigusr1_dumps_via_ops_plane(self, tmp_path, registry):
        ops = OpsPlane.from_config(
            ObsConfig(flight_recorder_rounds=4),
            registry=registry,
            bundle_dir=str(tmp_path),
        ).start()
        try:
            ops.recorder.record_round(round=1, record={"round": 1})
            os.kill(os.getpid(), signal.SIGUSR1)
            bundles = list(tmp_path.glob("flight_*_sigusr1.json"))
            assert len(bundles) == 1
        finally:
            ops.close()
        # handler restored: a second USR1 after close must not dump
        prev = signal.getsignal(signal.SIGUSR1)
        assert prev in (signal.SIG_DFL, signal.SIG_IGN) or prev is not None

    def test_breaker_open_transition_dumps(self, tmp_path, registry):
        ops = OpsPlane.from_config(
            ObsConfig(flight_recorder_rounds=4),
            registry=registry,
            bundle_dir=str(tmp_path),
        )
        breaker = CircuitBreaker(max_consecutive_failures=2, registry=registry)
        breaker.on_transition = ops.on_breaker_transition
        breaker.record_failure()
        assert not list(tmp_path.glob("*.json"))
        breaker.record_failure()  # closed -> open
        bundles = list(tmp_path.glob("flight_*_breaker_open.json"))
        assert len(bundles) == 1
        assert json.loads(bundles[0].read_text())["transition"]["to"] == "open"


# ---------------- decision explainability ----------------


def _sim():
    b = make_backend("mubench", seed=1)
    b.inject_imbalance("worker1")
    return b


def test_decide_explain_matches_decide_bitwise(registry):
    """The explain kernel's DECISION is the plain kernel's decision —
    same scores, same argmax, same key — across policies and rounds."""
    import jax.numpy as jnp

    from kubernetes_rescheduling_tpu.policies import POLICY_IDS
    from kubernetes_rescheduling_tpu.solver.round_loop import (
        decide,
        decide_explain,
    )

    backend = _sim()
    state = backend.monitor()
    graph = backend.comm_graph()
    thr = jnp.asarray(30.0)
    for policy in ("communication", "spread", "random"):
        pid = jnp.asarray(POLICY_IDS[policy])
        for r in range(3):
            key = jax.random.fold_in(jax.random.PRNGKey(7), r)
            plain = decide(state, graph, pid, thr, key)
            explained = decide_explain(state, graph, pid, thr, key, top_k=3)
            for a, b in zip(plain[:1] + plain[2:], explained[:1] + explained[2:5]):
                assert int(np.asarray(a)) == int(np.asarray(b))
            bundle = np.asarray(explained[5])
            assert bundle.shape == (6, 3)
            target_i = int(np.asarray(plain[4]))
            expl = greedy_explanation(
                bundle, state.node_names,
                round=r, seq=0, policy=policy,
                service="s", hazard_node="h",
                chosen=state.node_names[target_i] if target_i >= 0 else None,
            )
            assert explanation_consistent(expl)


def test_explanation_consistency_catches_wrong_chosen():
    expl = {
        "kind": "greedy",
        "chosen": "worker2",
        "candidates": [
            {"node": "worker1", "node_index": 0, "score": 5.0, "tiebreak": 0.0},
            {"node": "worker2", "node_index": 1, "score": 3.0, "tiebreak": 0.0},
        ],
    }
    assert not explanation_consistent(expl)
    expl["chosen"] = "worker1"
    assert explanation_consistent(expl)
    # ties resolve by tiebreak then LOWEST node index — the kernel's order
    tie = {
        "chosen": "worker1",
        "candidates": [
            {"node": "worker3", "node_index": 2, "score": 5.0, "tiebreak": 1.0},
            {"node": "worker1", "node_index": 0, "score": 5.0, "tiebreak": 1.0},
        ],
    }
    assert explanation_consistent(tie)
    assert explanation_consistent({"chosen": None, "candidates": []})


def test_controller_records_decisions_and_events(registry):
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=3, sleep_after_action_s=0.0,
        seed=1,
    )
    result = run_controller(_sim(), cfg, logger=logger)
    assert all(len(r.explanations) >= 1 for r in result.rounds)
    decisions = [r for r in logger.records if r["event"] == "decision"]
    assert len(decisions) == sum(len(r.explanations) for r in result.rounds)
    checked, bad = check_decisions(iter_decisions(logger.records))
    assert checked == len(decisions) and bad == []
    moved = [d for d in decisions if d.get("applied")]
    assert moved and all(d["landed"] for d in moved)
    # the as_dict/rounds.jsonl surface carries them too
    assert result.rounds[0].as_dict()["explanations"]


def test_controller_explain_off_is_explanation_free(registry):
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        seed=1, obs=ObsConfig(explain=False),
    )
    result = run_controller(_sim(), cfg, logger=logger)
    assert all(r.explanations == () for r in result.rounds)
    assert not [r for r in logger.records if r["event"] == "decision"]


def test_global_round_explanation_scores_match_wave_selection(registry):
    """Capped global rounds: the explanation's candidate scores are the
    wave-cap gains, and the chosen move is their argmax."""
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="global", max_rounds=2, sleep_after_action_s=0.0,
        seed=3, balance_weight=0.5, global_moves_cap=2,
    )
    result = run_controller(_sim(), cfg, logger=logger)
    expls = [e for r in result.rounds for e in r.explanations]
    assert expls
    for e in expls:
        assert e["kind"] == "global"
        assert explanation_consistent(e)
        if e["candidates"]:
            assert e["chosen"] == max(
                e["candidates"], key=lambda c: c["score"]
            )["node"]


def test_telemetry_explain_and_bundle_reports(tmp_path, registry):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    logger = StructuredLogger(name="t", path=tmp_path / "log.jsonl")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        seed=1,
    )
    fr = FlightRecorder(capacity=8, bundle_dir=tmp_path, registry=registry)
    result = run_controller(_sim(), cfg, logger=logger)
    for r in result.rounds:
        fr.record_round(round=r.round, digest="x", record=r.as_dict())
    bundle = fr.dump("crash", error="boom")

    import contextlib
    import io

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main(["telemetry", "explain", str(tmp_path / "log.jsonl")])
    assert rc == 0
    text = out.getvalue()
    assert "decisions re-derive" in text and "INCONSISTENT" not in text

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main(["telemetry", "bundle", str(bundle)])
    assert rc == 0
    text = out.getvalue()
    assert "reason=crash" in text and "explain-consistent" in text
    # the plain report auto-detects bundles too
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        assert cli_main(["telemetry", str(bundle)]) == 0
    assert "flight-recorder bundle" in out.getvalue()


def test_harness_serves_session_ops_plane(tmp_path, registry):
    """A bench session with serve_port wires one ops plane across cells:
    flight-recorder bundles land under the session dir, and the endpoint
    is shut down with the session."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=2,
        scenario="mubench",
        out_dir=str(tmp_path),
        seed=3,
        serve_port=0,
        load=LoadGenConfig(requests_per_phase=128, chunk=128),
    )
    summary = run_experiment(cfg)
    assert summary["runs"][0]["moves"] >= 0
    # rounds.jsonl carries the decision explanations (logger was attached)
    rounds_jsonl = list(
        tmp_path.glob("session_*/communication/run_1/rounds.jsonl")
    )
    recs = [
        json.loads(ln)
        for ln in rounds_jsonl[0].read_text().splitlines()
    ]
    assert all(r["explanations"] for r in recs)
    for e in (e for r in recs for e in r["explanations"]):
        assert explanation_consistent(e)
    # ... and the cost attribution, sum-consistent per round
    from kubernetes_rescheduling_tpu.telemetry.attribution import (
        check_attribution,
    )

    checked, bad = check_attribution(recs)
    assert checked == len(recs) and bad == []


# ---------------- config plumbing ----------------


def test_config_obs_toml_block(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "algorithm = 'communication'\n"
        "[obs]\n"
        "serve_port = 0\n"
        "explain_top_k = 5\n"
        "flight_recorder_rounds = 8\n"
        "slo_latency_p95_s = 0.25\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.obs.serve_port == 0
    assert cfg.obs.explain_top_k == 5
    assert cfg.obs.flight_recorder_rounds == 8
    assert cfg.obs.slo_latency_p95_s == 0.25


def test_config_obs_validation():
    with pytest.raises(ValueError):
        ObsConfig(serve_port=70000).validate()
    with pytest.raises(ValueError):
        ObsConfig(explain_top_k=0).validate()
    with pytest.raises(ValueError):
        ObsConfig(flight_recorder_rounds=0).validate()
    with pytest.raises(ValueError):
        RescheduleConfig(obs=ObsConfig(slo_window=1)).validate()


# ---------------- acceptance: the LIVE chaos soak ----------------


class _ProbingLogger(StructuredLogger):
    """Probes the live endpoint synchronously as loop events happen —
    deterministic observation points instead of a racing poller thread:
    /healthz on every skipped round (the breaker-open window) and on
    every breaker re-close; /metrics once mid-run."""

    def __post_init__(self):
        super().__post_init__()
        self.port = None
        self.skip_probes = []
        self.close_probes = []
        self.mid_metrics = None

    def log(self, level, event, **fields):
        super().log(level, event, **fields)
        if self.port is None:
            return
        if event == "round_skipped":
            status, body = _get(self.port, "/healthz")
            self.skip_probes.append(
                (fields.get("breaker"), status, json.loads(body))
            )
        elif event == "breaker" and fields.get("to") == "closed":
            status, body = _get(self.port, "/healthz")
            self.close_probes.append((status, json.loads(body)))
        elif event == "round":
            # overwrite each round: the kept capture is still mid-run (the
            # last executed round's scrape) but has seen the whole soak
            self.mid_metrics = _get(self.port, "/metrics")[1].decode()


def test_live_ops_soak_acceptance(tmp_path, registry):
    """ISSUE 3 acceptance: the seeded `soak` profile under a LIVE ops
    plane. /healthz goes unhealthy while the breaker is open and
    recovers when it re-closes; /metrics served mid-run parses and the
    final scrape matches the registry exactly; breaker-open leaves a
    flight-recorder bundle whose decision records pass the
    explain-consistency check for every executed round."""
    logger = _ProbingLogger(name="live-soak")
    ops = OpsPlane.from_config(
        ObsConfig(serve_port=0, flight_recorder_rounds=64),
        registry=registry,
        logger=logger,
        bundle_dir=str(tmp_path / "fr"),
    ).start()
    logger.port = ops.server.port
    try:
        from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy

        report = run_chaos_soak(
            profile="soak",
            rounds=35,
            seed=1,
            chaos_seed=0,
            retry=RetryPolicy(max_attempts=1),
            max_consecutive_failures=3,
            breaker_cooldown_rounds=2,
            failure_budget_per_round=2,
            logger=logger,
            registry=registry,
            ops=ops,
        )
        # the soak's own invariants still hold under observation
        assert report["records"] + report["skipped_rounds"] == 35
        assert report["breaker_opens"] >= 1 and report["breaker_closes"] >= 1
        assert report["skipped_rounds"] >= 1

        # /healthz went unhealthy while the breaker was open ...
        open_probes = [p for p in logger.skip_probes if p[0] == "open"]
        assert open_probes, "no skipped-round probe saw the open breaker"
        for breaker_state, status, payload in open_probes:
            assert status == 503
            assert payload["status"] == "unhealthy"
            assert payload["breaker"] == "open"
        # ... and recovered the moment it re-closed
        assert logger.close_probes
        for status, payload in logger.close_probes:
            assert status == 200
            assert payload["breaker"] == "closed"

        # /metrics mid-run parses and carries the loop's series
        assert logger.mid_metrics is not None
        families, samples = assert_exposition_conformant(logger.mid_metrics)
        for name in ("rounds_total", "chaos_faults_total", "decision_seconds"):
            assert name in families

        # the final scrape is EXACTLY the registry (loop is quiescent)
        final = _get(logger.port, "/metrics")[1].decode()
        assert final == registry.expose()

        # health settles with the breaker's final state
        status, body = _get(logger.port, "/healthz")
        payload = json.loads(body)
        assert payload["rounds"] == report["records"]
        assert payload["skipped_rounds"] == report["skipped_rounds"]
        if ops.health.breaker.state != "open":
            assert status == 200
        else:
            assert status == 503

        # breaker-open dumped a bundle; its decisions are explain-consistent
        bundles = sorted((tmp_path / "fr").glob("flight_*_breaker_open.json"))
        assert len(bundles) == report["breaker_opens"]
        bundle = json.loads(bundles[-1].read_text())
        executed = [r for r in bundle["rounds"] if not r.get("skipped")]
        assert executed
        for entry in executed:
            assert entry["digest"]  # snapshot digest recorded
            expls = entry["record"]["explanations"]
            assert expls, f"round {entry['round']} recorded no decisions"
        decisions = iter_decisions(bundle["rounds"])
        checked, bad = check_decisions(decisions)
        assert checked >= len(executed)
        assert bad == [], f"inconsistent decisions in bundle: {bad}"
        # the watchdog stayed clean: steady-state kernels never retraced
        assert ops.watchdog.healthy, ops.watchdog.active
    finally:
        ops.close()
