"""Elastic topologies: churn events, shape buckets, and the
no-retrace-under-churn contract.

Pinned invariants:

- **mask twins** — for every kernel taking validity masks, a padded +
  masked problem is bit-exact with the unpadded problem of the same
  live size (greedy decide across all five policies, the explain twin,
  the attribution kernel, objectives, and both fleet planes);
- **no-churn regression** — the elastic refactor of the simulator left
  a static run bit-identical to the pre-elastic code (golden-pinned
  trajectory + final placement digest);
- **steady-state traces** — churn within a bucket never retraces: every
  instrumented kernel compiles exactly ``1 + bucket promotions`` times
  (a promotion landing before a kernel's first compile folds in);
- **acceptance soak** — a seeded 30-round ``diurnal-autoscale`` run
  (replicas ×0.5–×2, one node drain/add cycle) completes with pinned
  traces, sum-consistent attribution every round, and full round
  accounting;
- **fleet isolation** — churn on one tenant leaves the other tenants'
  trajectories bit-identical to a churn-free fleet run.
"""

import hashlib
import json
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.fleet import make_fleet
from kubernetes_rescheduling_tpu.backends.sim import SimBackend, LoadModel
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.bench.loadgen import service_rate_series
from kubernetes_rescheduling_tpu.config import (
    ElasticConfig,
    FleetConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.workmodel import (
    ServiceSpec,
    Workmodel,
    mubench_workmodel_c,
)
from kubernetes_rescheduling_tpu.elastic import (
    ChurnEngine,
    ShapeBuckets,
    bucket_capacity,
    device_graph,
    device_view,
)
from kubernetes_rescheduling_tpu.objectives.metrics import (
    capacity_violation,
    communication_cost,
    communication_cost_attribution,
    load_std,
    node_pair_cost_matrix,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.solver.round_loop import decide, decide_explain
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.attribution import (
    check_attribution,
    decode_attribution,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import (
    RULE_RETRACE,
    SLORules,
    Watchdog,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


# ---------------------------------------------------------------- buckets


def test_bucket_capacity_quantization():
    assert bucket_capacity(0) == 8
    assert bucket_capacity(1) == 8
    assert bucket_capacity(8) == 8
    assert bucket_capacity(9) == 16
    assert bucket_capacity(1000) == 1024
    assert bucket_capacity(3, floor=4) == 4
    with pytest.raises(ValueError):
        bucket_capacity(-1)


def test_shape_buckets_promote_once_per_fit_and_never_shrink():
    b = ShapeBuckets(floor=8)
    # initial sizing is a compile, not a promotion
    assert b.fit(services=20, nodes=3, pods=21) is False
    assert (b.services, b.nodes, b.pods) == (32, 8, 32)
    assert b.promotions == 0
    # in-bucket churn: no promotion
    assert b.fit(services=25, nodes=5, pods=30) is False
    # two axes outgrow in ONE fit -> one promotion (one new signature)
    assert b.fit(services=40, nodes=3, pods=40) is True
    assert b.promotions == 1
    assert (b.services, b.pods) == (64, 64)
    # shrink never happens
    assert b.fit(services=5, nodes=1, pods=5) is False
    assert (b.services, b.nodes, b.pods) == (64, 8, 64)


def test_device_view_strips_names_only():
    backend = make_backend("mubench", seed=0)
    state = backend.monitor()
    dev = device_view(state)
    assert dev.node_names == () and dev.pod_names == ()
    assert dev.pod_node is state.pod_node  # same arrays, no copies
    graph = backend.comm_graph()
    dg = device_graph(graph)
    assert dg.names == () and dg.adj is graph.adj
    # idempotent (already-stripped views return themselves)
    assert device_view(dev) is dev
    assert device_graph(dg) is dg


# ------------------------------------------------------------- mask twins


def _twin_problem(seed=2):
    """The same live cluster twice: exact shapes vs bucket-padded shapes
    (node 3→8, pod 21→64, service 20→32). Same seed → identical rng
    placement stream → identical live arrays."""
    exact = make_backend("mubench", seed=seed)
    exact.inject_imbalance(exact.node_names[0])
    padded = make_backend("mubench", seed=seed)
    padded.set_capacities(node=8, pod=64, service=32)
    padded.inject_imbalance(padded.node_names[0])
    return (
        exact.monitor(), exact.comm_graph(),
        padded.monitor(), padded.comm_graph(),
    )


def test_mask_twin_greedy_decide_all_policies():
    """The greedy decision kernel: padded+masked bit-exact with the
    unpadded twin for every policy — including the PRNG `random` policy
    (partitionable threefry makes the padded gumbel draw a prefix
    extension of the unpadded one)."""
    st, gr, pst, pgr = _twin_problem()
    thr = jnp.asarray(30.0)
    for name, pid in POLICY_IDS.items():
        key = jax.random.PRNGKey(7)
        a = decide(st, gr, jnp.asarray(pid), thr, key)
        b = decide(pst, pgr, jnp.asarray(pid), thr, key)
        for ai, bi in zip(a[:1] + a[2:], b[:1] + b[2:]):  # scalars
            assert int(ai) == int(bi), name
        n = st.num_nodes
        assert np.array_equal(np.asarray(a[1]), np.asarray(b[1])[:n]), name
        assert not np.asarray(b[1])[n:].any(), name  # padded nodes never hazard


def test_mask_twin_decide_explain_bundle():
    st, gr, pst, pgr = _twin_problem()
    thr = jnp.asarray(30.0)
    key = jax.random.PRNGKey(3)
    pid = jnp.asarray(POLICY_IDS["communication"])
    *a, bundle_a = decide_explain(st, gr, pid, thr, key, top_k=3)
    *b, bundle_b = decide_explain(pst, pgr, pid, thr, key, top_k=3)
    assert int(a[0]) == int(b[0]) and int(a[4]) == int(b[4])
    assert int(a[2]) == int(b[2]) and int(a[3]) == int(b[3])
    # k = min(3, N) = 3 on both sides; every recorded row bit-exact
    assert np.array_equal(np.asarray(bundle_a), np.asarray(bundle_b))


def test_mask_twin_objectives():
    st, gr, pst, pgr = _twin_problem()
    assert float(communication_cost(st, gr)) == float(
        communication_cost(pst, pgr)
    )
    assert float(load_std(st)) == float(load_std(pst))
    assert float(capacity_violation(st)) == float(capacity_violation(pst))
    m = np.asarray(node_pair_cost_matrix(st, gr))
    pm = np.asarray(node_pair_cost_matrix(pst, pgr))
    n = st.num_nodes
    assert np.array_equal(m, pm[:n, :n])
    assert not pm[n:, :].any() and not pm[:, n:].any()


def test_mask_twin_attribution_kernel():
    st, gr, pst, pgr = _twin_problem()
    k = 6
    a = decode_attribution(
        np.asarray(communication_cost_attribution(st, gr, top_k=k)),
        node_names=st.node_names, service_names=gr.names,
        top_k=k, num_nodes=st.num_nodes, num_services=gr.num_services,
    )
    b = decode_attribution(
        np.asarray(communication_cost_attribution(pst, pgr, top_k=k)),
        node_names=pst.node_names, service_names=pgr.names,
        top_k=k, num_nodes=pst.num_nodes, num_services=pgr.num_services,
    )
    assert a["total"] == b["total"] and a["tail"] == b["tail"]
    ea = [(e["src_service"], e["dst_service"], e["cost"]) for e in a["edges"]]
    eb = [(e["src_service"], e["dst_service"], e["cost"]) for e in b["edges"]]
    assert ea == eb


def test_mask_twin_fleet_planes():
    """Both fleet device planes over padded tenants reproduce the solo
    kernel on the unpadded twin, row for row."""
    from kubernetes_rescheduling_tpu.parallel.fleet import fleet_solve_dp
    from kubernetes_rescheduling_tpu.solver.fleet import (
        ROW_MOST, ROW_SERVICE, ROW_TARGET, ROW_VICTIM,
        fleet_solve, stack_tenants,
    )

    st, gr, pst, pgr = _twin_problem()
    _, _, pst2, pgr2 = _twin_problem(seed=5)
    st2 = make_backend("mubench", seed=5)
    st2.inject_imbalance(st2.node_names[0])
    est2, egr2 = st2.monitor(), st2.comm_graph()

    states = stack_tenants([device_view(pst), device_view(pst2)])
    graphs = stack_tenants([device_graph(pgr), device_graph(pgr2)])
    pid = jnp.asarray(POLICY_IDS["communication"])
    thr = jnp.asarray(30.0)
    keys = jnp.stack([jax.random.PRNGKey(11), jax.random.PRNGKey(12)])
    mask = jnp.ones((2,), bool)

    for plane in (fleet_solve, fleet_solve_dp):
        dec, _hz = plane(states, graphs, pid, thr, keys, mask)
        dec = np.asarray(dec)
        for row, (est, egr, key) in enumerate(
            [(st, gr, keys[0]), (est2, egr2, keys[1])]
        ):
            most, _m, victim, svc, target = decide(est, egr, pid, thr, key)
            assert dec[row, ROW_MOST] == int(most)
            assert dec[row, ROW_VICTIM] == int(victim)
            assert dec[row, ROW_SERVICE] == int(svc)
            assert dec[row, ROW_TARGET] == int(target)


# ------------------------------------------------- no-churn regression


def test_no_churn_run_bit_identical_to_pre_elastic_sim():
    """Satellite regression: the mutable-node/pod-set refactor of
    SimBackend left the static path byte-for-byte identical — golden
    trajectory + placement digest captured from the pre-elastic code."""
    backend = make_backend("mubench", seed=3)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=5,
        sleep_after_action_s=0.0, seed=3,
    )
    res = run_controller(backend, cfg, key=jax.random.PRNGKey(3))
    traj = [
        (r.round, r.moved, r.service, r.target,
         r.communication_cost, round(r.load_std, 6))
        for r in res.rounds
    ]
    assert traj == [
        (1, True, "s0", "worker2", 4.0, 37.104767),
        (2, True, "s1", "worker2", 7.0, 34.235298),
        (3, True, "s2", "worker2", 6.0, 31.48699),
        (4, True, "s3", "worker2", 10.0, 28.894444),
        (5, True, "s4", "worker2", 9.0, 26.503405),
    ]
    final = backend.monitor()
    digest = hashlib.sha1(
        np.asarray(final.pod_node).tobytes()
        + np.asarray(final.pod_valid).tobytes()
    ).hexdigest()
    assert digest == "704ae98df34a8fcd626b0dfe47ec045957223f24"
    assert all(r.churn is None for r in res.rounds)


# --------------------------------------------------------- sim mutators


def _tiny_backend(seed=0, **kw):
    wm = Workmodel(
        services=(
            ServiceSpec(name="a", callees=("b",)),
            ServiceSpec(name="b", callees=("c",)),
            ServiceSpec(name="c"),
        )
    )
    return SimBackend(
        workmodel=wm, node_names=["n0", "n1"], seed=seed,
        load=LoadModel(entry_service="a"), **kw,
    )


def test_sim_teardown_compacts_indices_and_graph():
    b = _tiny_backend()
    b.teardown_service("b")
    g = b.comm_graph()
    assert g.names == ("a", "c")
    st = b.monitor()
    svc = np.asarray(st.pod_service)[np.asarray(st.pod_valid)]
    assert sorted(g.names[int(s)] for s in svc) == ["a", "c"]
    with pytest.raises(ValueError):
        b.teardown_service("b")


def test_sim_scale_and_deploy_track_replicas():
    b = _tiny_backend()
    b.scale_replicas("a", 3)
    assert {s.name: s.replicas for s in b.workmodel.services}["a"] == 3
    b.scale_replicas("a", 1)
    assert b.live_counts()["pods"] == 3
    b.deploy_service(ServiceSpec(name="d", callees=("a",), replicas=2))
    assert b.live_counts() == {"services": 4, "nodes": 2, "pods": 5}
    g = b.comm_graph()
    assert g.adj[g.names.index("d"), g.names.index("a")] > 0
    with pytest.raises(ValueError):
        b.deploy_service(ServiceSpec(name="d"))


def test_sim_drain_reschedules_add_grows():
    b = _tiny_backend()
    b.add_node("n2")
    assert b.live_counts()["nodes"] == 3
    b.drain_node("n0")
    st = b.monitor()
    nodes = np.asarray(st.pod_node)[np.asarray(st.pod_valid)]
    alive = {b.node_names.index(n) for n in b.alive_node_names()}
    assert set(int(x) for x in nodes) <= alive  # drained pods re-placed
    b.add_node("n0")  # re-adding a drained name revives it
    assert "n0" in b.alive_node_names()


# ------------------------------------------------------------ the engine


def test_engine_event_stream_is_seeded_deterministic():
    logs = []
    for _ in range(2):
        backend = _tiny_backend(seed=1)
        eng = ChurnEngine("deploy-waves", seed=9, registry=MetricsRegistry())
        eng.bind(backend, 12)
        for rnd in range(1, 13):
            eng.step(rnd)
        logs.append(eng.events_log)
    assert logs[0] == logs[1]
    assert any(e["kind"] == "service_deploy" for e in logs[0])
    assert any(e["kind"] == "service_teardown" for e in logs[0])


def test_engine_profiles_produce_their_kinds():
    kinds_by_profile = {}
    for profile in ("steady", "diurnal-autoscale", "node-flap"):
        backend = make_backend("mubench", seed=1)
        eng = ChurnEngine(profile, seed=3, registry=MetricsRegistry())
        eng.bind(backend, 20)
        for rnd in range(1, 21):
            eng.step(rnd)
        kinds_by_profile[profile] = {e["kind"] for e in eng.events_log}
    assert kinds_by_profile["steady"] <= {"replica_scale"}
    assert "replica_scale" in kinds_by_profile["diurnal-autoscale"]
    assert "node_drain" in kinds_by_profile["diurnal-autoscale"]
    assert "node_add" in kinds_by_profile["diurnal-autoscale"]
    assert "node_drain" in kinds_by_profile["node-flap"]


def test_engine_promotion_counts_and_invalidates_solver_caches(registry):
    backend = _tiny_backend(seed=0)
    backend._solver_caches = {("sparse_graph", None): {"graph": object()}}
    eng = ChurnEngine("deploy-waves", seed=0, bucket_floor=4, registry=registry)
    eng.bind(backend, 30)
    assert backend.service_capacity == 4  # 3 services -> floor bucket
    promoted_rounds = []
    for rnd in range(1, 8):
        eng.step(rnd)
        if eng.promoted:
            promoted_rounds.append(rnd)
    assert promoted_rounds, "deploy waves past 4 services must promote"
    assert eng.buckets.promotions == len(promoted_rounds)
    assert backend._solver_caches == {}  # promotion cleared the slots
    assert backend.service_capacity >= eng.buckets.services
    # telemetry: the counter matches the bucket accounting
    snap = {
        (r["metric"], tuple(sorted(r["labels"].items()))): r.get("value", 0)
        for r in registry.snapshot()
    }
    assert snap[("bucket_promotions_total", ())] == eng.buckets.promotions
    assert snap[("bucket_capacity", (("axis", "services"),))] == eng.buckets.services


def test_engine_requires_elastic_mutators():
    class NotASim:
        pass

    eng = ChurnEngine("steady", registry=MetricsRegistry())
    with pytest.raises(TypeError, match="elastic mutators"):
        eng.bind(NotASim(), 10)


# ------------------------------------------------------ rate series


def test_rate_profile_resamples_not_truncates():
    wm = mubench_workmodel_c()
    rp = service_rate_series(wm, amplitude=2.0, steps=8, phase_jitter=0.0)
    # a 30-round run over the 8-point shape sweeps the WHOLE profile:
    # the peak (~x2) and the trough (~x0.5) both appear. The truncation
    # idiom (shape[:rounds] index) would replay only the profile's head.
    factors = [rp.factors(r, 30)["s0"] for r in range(1, 31)]
    assert max(factors) > 1.8 and min(factors) < 0.6
    # resampling is horizon-independent: a 10-round run sweeps it too
    short = [rp.factors(r, 10)["s0"] for r in range(1, 11)]
    assert max(short) > 1.7 and min(short) < 0.65


def test_rate_profile_per_replica_follows_live_counts():
    wm = mubench_workmodel_c()
    rp = service_rate_series(wm, entry_rps=100.0, steps=8, phase_jitter=0.0)
    total = rp.at(4, 10)["s0"]
    one = rp.per_replica(4, 10, {"s0": 1})["s0"]
    four = rp.per_replica(4, 10, {"s0": 4})["s0"]
    assert one == pytest.approx(total)
    assert four == pytest.approx(total / 4)  # same offered load, split


def test_rate_profile_base_rates_propagate_call_graph():
    wm = mubench_workmodel_c()
    rp = service_rate_series(wm, entry_rps=100.0)
    rates = dict(zip(rp.names, rp.base_rps))
    assert rates["s0"] == 100.0
    assert rates["s1"] == 100.0   # s0 -> s1
    assert rates["s2"] == 100.0   # s1 -> s2
    assert rates["s18"] == 100.0  # s0->s1->s15->s18


# ------------------------------------------------- controller invariants


def _churn_run(profile, rounds, *, logger=None, seed=1, registry=None):
    backend = make_backend("mubench", seed=seed)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=rounds,
        sleep_after_action_s=0.0, seed=seed,
        elastic=ElasticConfig(profile=profile, seed=7),
    )
    res = run_controller(
        backend, cfg, key=jax.random.PRNGKey(seed), logger=logger,
        registry=registry,
    )
    return backend, res


def _traces(registry, fn):
    return int(
        registry.counter("jax_traces_total", labelnames=("fn",))
        .labels(fn=fn).value
    )


def test_steady_churn_one_trace(registry):
    """The quiet-cluster invariant: in-bucket churn reuses ONE compiled
    decision kernel for the whole run."""
    backend, res = _churn_run("steady", 10, registry=registry)
    assert len(res.rounds) + res.skipped_rounds == 10
    promotions = res.rounds[-1].churn["promotions"]
    assert promotions == 0
    assert _traces(registry, "controller_decide") == 1
    assert all(r.churn is not None for r in res.rounds)


def test_acceptance_diurnal_autoscale_soak(registry):
    """THE acceptance soak: 30 seeded rounds under diurnal-autoscale
    (replicas ×0.5–×2 tracking the rate series, one node drain/add
    cycle) with explain + attribution live. Every instrumented kernel
    compiles exactly 1 + (promotions after its first compile) times,
    attribution stays sum-consistent every round, and every round is
    accounted."""
    logger = StructuredLogger(name="elastic-soak")
    backend, res = _churn_run(
        "diurnal-autoscale", 30, logger=logger, registry=registry
    )
    assert len(res.rounds) + res.skipped_rounds == 30
    assert res.rounds, "soak produced no executed rounds"
    # churn really happened: scaling events and the drain/add cycle
    events = [e for r in res.rounds for e in (r.churn or {}).get("events", ())]
    kinds = {e["kind"] for e in events}
    assert "replica_scale" in kinds
    assert "node_drain" in kinds and "node_add" in kinds
    # trace accounting: promotions folded into the first compile do not
    # retrace; every later promotion retraces exactly once
    first = res.rounds[0].churn["promotions"]
    final = res.rounds[-1].churn["promotions"]
    expected = 1 + (final - first)
    assert _traces(registry, "controller_decide_explain") == expected
    # the round-end kernel (cost + load-std + attribution bundle in one
    # program) compiles at STARTUP — before round 1's churn — so its
    # allowance counts every promotion since the run began, not since
    # the first decide
    assert _traces(registry, "controller_round_end") == 1 + final
    # attribution: sum-consistent EVERY round (the PR-5 invariant holds
    # under churn, across the bucket promotion)
    checked, bad = check_attribution([r.as_dict() for r in res.rounds])
    assert checked == len(res.rounds) and bad == []
    # the replica swing really spans the x0.5-x2 band at some point
    pods = [r.churn["live_pods"] for r in res.rounds]
    assert max(pods) > min(pods)
    # gauges + counters landed
    snap = {
        (r["metric"], tuple(sorted(r["labels"].items()))): r.get("value", 0)
        for r in registry.snapshot()
    }
    assert snap[("live_services", ())] == res.rounds[-1].churn["live_services"]
    assert ("bucket_capacity", (("axis", "pods"),)) in snap
    # the counter may exceed the recorded events (skipped rounds churn
    # too but leave no RoundRecord) — never undercount
    total_counted = sum(
        v for (m, _l), v in snap.items() if m == "churn_events_total"
    )
    assert total_counted >= len(events) > 0


@pytest.mark.slow  # 60-round two-profile soak; the 30-round diurnal pin stays fast in test_acceptance_diurnal_autoscale_soak above
def test_long_deploy_waves_soak(registry):
    """Structural churn endurance: 60 rounds of deploy-waves — the comm
    graph grows and shrinks repeatedly — with the same trace pin."""
    logger = StructuredLogger(name="elastic-waves")
    backend, res = _churn_run(
        "deploy-waves", 60, logger=logger, registry=registry
    )
    assert len(res.rounds) + res.skipped_rounds == 60
    first = res.rounds[0].churn["promotions"]
    final = res.rounds[-1].churn["promotions"]
    # <=: an earlier test's run may have compiled these bucket shapes
    # already (process-wide jit cache) — the pin is NO UNEXPLAINED traces
    assert _traces(registry, "controller_decide_explain") <= 1 + (final - first)
    checked, bad = check_attribution([r.as_dict() for r in res.rounds])
    assert checked == len(res.rounds) and bad == []
    assert backend.live_counts()["services"] != 20  # waves really landed


def test_node_flap_churn_keeps_loop_alive(registry):
    backend, res = _churn_run("node-flap", 14, registry=registry)
    assert len(res.rounds) + res.skipped_rounds == 14
    kinds = {
        e["kind"]
        for r in res.rounds
        for e in (r.churn or {}).get("events", ())
    }
    assert "node_drain" in kinds
    # drained capacity returns: the run ends with every node alive again
    # or at most the currently-flapped one down
    assert len(backend.alive_node_names()) >= len(backend.node_names) - 1


class _FlakyMonitor:
    """Backend wrapper failing monitor() on exact call numbers — the
    deterministic way to hit the churn re-mask path's failure branch."""

    def __init__(self, inner, fail_calls):
        self.inner = inner
        self.calls = 0
        self.fail_calls = set(fail_calls)

    def monitor(self):
        self.calls += 1
        if self.calls in self.fail_calls:
            raise ConnectionError("flaky monitor")
        return self.inner.monitor()

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_remask_debt_survives_a_skipped_churn_round(registry):
    """A churn round whose re-mask monitor fails becomes a counted skip,
    and the NEXT executed round still re-masks (and re-anchors the
    provenance model) before deciding — graph-changing churn can never
    be silently decided against the pre-churn snapshot."""
    from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy

    inner = make_backend("mubench", seed=6)
    inner.inject_imbalance(inner.node_names[0])
    backend = _FlakyMonitor(inner, fail_calls={2})  # the round-1 re-mask
    logger = StructuredLogger(name="flaky-churn")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=4,
        sleep_after_action_s=0.0, seed=6,
        retry=RetryPolicy(max_attempts=1, base_delay_s=0.0),
        elastic=ElasticConfig(profile="deploy-waves", seed=5),
    )
    res = run_controller(
        backend, cfg, key=jax.random.PRNGKey(6), logger=logger,
        registry=registry,
    )
    assert res.skipped_rounds == 1  # round 1: churned, dark, counted
    assert len(res.rounds) + res.skipped_rounds == 4
    first = res.rounds[0]
    # the first EXECUTED round already sees the deployed wave (the
    # re-mask debt was settled before deciding) AND carries the skipped
    # round's events (pending-churn flush: rounds.jsonl never shows a
    # live-count jump with no events explaining it)
    assert first.churn["live_services"] > 20
    assert any(e["round"] == 1 for e in first.churn["events"])
    checked, bad = check_attribution([r.as_dict() for r in res.rounds])
    assert checked == len(res.rounds) and bad == []


@pytest.mark.slow  # churn trace accounting stays pinned fast by
# test_steady_churn_one_trace and test_acceptance_diurnal_autoscale_soak
# above; this is the global-path repro variant (the code-review
# regression) with its own ~30 s global_assign compile
def test_global_rounds_under_churn_stay_trace_stable(registry):
    """The global solver path threads the same name-stripped device
    views as the greedy path: churn that renames pods/services must not
    retrace `global_assign` beyond the counted bucket promotions (the
    code-review repro: 4 traces in 6 rounds before the fix)."""
    backend = make_backend("mubench", seed=8)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        # 4 churny rounds suffice: the pre-fix repro retraced on EVERY
        # churn round (4 traces in 6 rounds), so a per-round retrace
        # still shows as >= 2 traces here
        algorithm="global", max_rounds=4,
        sleep_after_action_s=0.0, seed=8, balance_weight=0.5,
        elastic=ElasticConfig(profile="diurnal-autoscale", seed=2),
    )
    res = run_controller(
        backend, cfg, key=jax.random.PRNGKey(8), registry=registry
    )
    assert len(res.rounds) + res.skipped_rounds == 4
    promos = max((r.churn["promotions"] for r in res.rounds if r.churn), default=0)
    assert _traces(registry, "global_assign") <= 1 + promos


def test_resume_fast_forwards_the_churn_stream(tmp_path):
    """Checkpoint resume under churn: the engine replays the completed
    rounds' events on the rebuilt backend, so the resumed run's topology
    and event stream are bit-identical to the uninterrupted run's."""

    def build():
        b = make_backend("mubench", seed=4)
        b.inject_imbalance(b.node_names[0])
        return b

    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=6,
        sleep_after_action_s=0.0, seed=4,
        elastic=ElasticConfig(profile="deploy-waves", seed=5),
    )
    full_backend = build()
    full = run_controller(
        full_backend, cfg, key=jax.random.PRNGKey(4),
        checkpoint_dir=str(tmp_path / "full"),
    )

    class Boom(Exception):
        pass

    def crash_at_3(rec, _state):
        if rec.round == 3:
            raise Boom()

    crash_dir = str(tmp_path / "crash")
    with pytest.raises(Boom):
        run_controller(
            build(), cfg, key=jax.random.PRNGKey(4),
            checkpoint_dir=crash_dir, on_round=crash_at_3,
        )
    resumed_backend = build()
    resumed = run_controller(
        resumed_backend, cfg, key=jax.random.PRNGKey(4),
        checkpoint_dir=crash_dir,
    )
    assert resumed.resumed_from_round == 3  # round 3 replays

    def traj(rounds):
        return [
            (r.round, r.moved, r.service, r.target,
             r.communication_cost, r.churn)
            for r in rounds
        ]

    assert traj(resumed.rounds) == traj(full.rounds[2:])
    assert resumed_backend.live_counts() == full_backend.live_counts()


# ------------------------------------------------------------ fleet churn


def _fleet_traj(result, name):
    return [
        (r.round, r.moved, r.service, r.target,
         r.communication_cost, r.load_std)
        for r in result.results[name].rounds
    ]


def test_fleet_churn_isolated_to_its_tenant():
    """Acceptance: churn on tenant 1 (deploy-waves — graph-changing,
    bucket-padding) leaves tenants 0 and 2 bit-identical with a
    churn-free fleet run, across the padded/unpadded representation
    change (the mask twins make it exact)."""

    def run(profile):
        fleet = make_fleet("mubench", 3, seed=5)
        fleet.inject_imbalance()
        cfg = RescheduleConfig(
            algorithm="communication", max_rounds=8,
            sleep_after_action_s=0.0, seed=5,
            fleet=FleetConfig(tenants=3),
            elastic=ElasticConfig(profile=profile, seed=11, tenants=(1,)),
        )
        return run_fleet_controller(fleet, cfg, key=jax.random.PRNGKey(5))

    base = run("none")
    churned = run("deploy-waves")
    for name in ("tenant0", "tenant2"):
        assert _fleet_traj(base, name) == _fleet_traj(churned, name)
        assert all(r.churn is None for r in churned.results[name].rounds)
    t1 = churned.results["tenant1"].rounds
    assert any(r.churn and r.churn["events"] for r in t1)
    # accounting holds per tenant under churn
    for name, r in churned.results.items():
        assert len(r.rounds) + r.skipped_rounds == 8


def test_fleet_shared_buckets_keep_tenants_stackable(registry):
    """A promotion on the churned tenant re-pads the WHOLE fleet (one
    shared bucket set) — the loop keeps stacking and the batched kernel
    retraces at most once per promotion."""
    fleet = make_fleet("mubench", 2, seed=2)
    fleet.inject_imbalance()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=10,
        sleep_after_action_s=0.0, seed=2,
        fleet=FleetConfig(tenants=2),
        # diurnal autoscaling doubles replicas -> pods outgrow the first
        # bucket mid-run on the churned tenant
        elastic=ElasticConfig(
            profile="diurnal-autoscale", seed=3, tenants=(0,), bucket_floor=8
        ),
    )
    result = run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(2), registry=registry,
    )
    for name, r in result.results.items():
        assert len(r.rounds) + r.skipped_rounds == 10
    t0 = result.results["tenant0"].rounds
    promos = max((r.churn["promotions"] for r in t0 if r.churn), default=0)
    traces = _traces(registry, "fleet_solve")
    assert 1 <= traces <= 1 + promos


# ------------------------------------------------------- watchdog rule


def _round_rec(cost=1.0, lat=0.01, promotions=None):
    churn = None if promotions is None else {"promotions": promotions}
    return types.SimpleNamespace(
        decision_latency_s=lat, communication_cost=cost,
        attribution=None, churn=churn,
    )


def test_watchdog_retrace_rule_allows_promotions(registry):
    wd = Watchdog(SLORules(max_retraces=1), registry=registry)
    tr = registry.counter(
        "jax_traces_total", "t", labelnames=("fn",)
    ).labels(fn="k")
    tr.inc()  # first compile
    assert wd.observe_round(_round_rec(promotions=0)) == []
    # a bucket promotion retraces the kernel: allowed, not a violation
    tr.inc()
    assert wd.observe_round(_round_rec(promotions=1)) == []
    assert RULE_RETRACE not in wd.active
    # a retrace with NO promotion to explain it: violation
    tr.inc()
    raised = wd.observe_round(_round_rec(promotions=1))
    assert [v["rule"] for v in raised] == [RULE_RETRACE]
    assert wd.active[RULE_RETRACE]["promotions_allowed"] == 1


def test_watchdog_rebase_clears_promotion_allowance(registry):
    wd = Watchdog(SLORules(max_retraces=1), registry=registry)
    assert wd.observe_round(_round_rec(promotions=5)) == []  # baselined
    wd.rebase()
    assert wd._promo_allow == 0 and wd._promo_seen is None


# ------------------------------------------------------- config + CLI


def test_elastic_config_validation():
    with pytest.raises(ValueError, match="churn profile"):
        ElasticConfig(profile="tsunami").validate()
    with pytest.raises(ValueError, match="bucket_floor"):
        ElasticConfig(bucket_floor=0).validate()
    with pytest.raises(ValueError, match="tenants"):
        ElasticConfig(tenants=(-1,)).validate()
    ElasticConfig(profile="steady", tenants=(0, 2)).validate()
    with pytest.raises(ValueError, match="sim backend"):
        RescheduleConfig(
            backend="k8s", elastic=ElasticConfig(profile="steady")
        ).validate()


def test_elastic_config_from_toml(tmp_path):
    f = tmp_path / "cfg.toml"
    f.write_text(
        'algorithm = "communication"\n'
        "[elastic]\n"
        'profile = "node-flap"\n'
        "seed = 4\n"
        "bucket_floor = 16\n"
        "tenants = [1, 3]\n"
    )
    cfg = RescheduleConfig.from_toml(f)
    assert cfg.elastic == ElasticConfig(
        profile="node-flap", seed=4, bucket_floor=16, tenants=(1, 3)
    )


def test_experiment_config_rejects_bad_churn():
    from kubernetes_rescheduling_tpu.bench.harness import ExperimentConfig

    with pytest.raises(ValueError, match="churn profile"):
        ExperimentConfig(churn_profile="tsunami")
    with pytest.raises(ValueError, match="sim backend"):
        ExperimentConfig(backend="k8s", churn_profile="steady")
    # the weight estimator's call plan is frozen at cell start — under
    # churn it would silently steer solves with the stale topology
    with pytest.raises(ValueError, match="observe_weights"):
        ExperimentConfig(churn_profile="steady", observe_weights=True)


def test_churn_wave_advances_clock_once():
    """A busy churn round reconciles as ONE wave (the apply_pod_moves
    rule): simulated time advances by reconcile_delay_s per churny
    round, never events × delay — else the harness's clock-driven load
    segments would inflate ~100x under diurnal autoscaling."""
    backend = make_backend("mubench", seed=1)
    eng = ChurnEngine(
        "diurnal-autoscale", seed=3, registry=MetricsRegistry()
    )
    eng.bind(backend, 10)
    before = backend.clock_s
    applied = eng.step(2)  # mid-sinusoid: many services rescale at once
    assert len(applied) > 1
    assert backend.clock_s - before == pytest.approx(backend.reconcile_delay_s)
    # a quiet round costs nothing
    before = backend.clock_s
    if not eng.step(3):
        assert backend.clock_s == before


def test_cli_churn_flags_smoke(capsys):
    from kubernetes_rescheduling_tpu import cli

    rc = cli.main(
        [
            "reschedule", "--scenario", "mubench", "--rounds", "3",
            "--imbalance", "--churn-profile", "steady",
            "--churn-seed", "2",
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert len(out["rounds"]) + out["skipped_rounds"] == 3
    assert out["rounds"][0]["churn"] is not None


def test_cli_rejects_churn_on_k8s():
    from kubernetes_rescheduling_tpu import cli

    with pytest.raises(SystemExit, match="sim backend"):
        cli.main(
            [
                "reschedule", "--backend", "k8s",
                "--churn-profile", "steady",
            ]
        )


# --------------------------------------------------------- harness cell


@pytest.mark.slow  # full harness cell with load phases; the controller-level churn pins stay fast in test_steady_churn_one_trace / the acceptance soak above
def test_harness_churn_cell_records_rounds(tmp_path):
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    cfg = ExperimentConfig(
        algorithms=("communication",), repeats=1, rounds=3,
        scenario="mubench", out_dir=str(tmp_path),
        churn_profile="steady", churn_seed=1,
        load=LoadGenConfig(requests_per_phase=256, chunk=256),
    )
    summary = run_experiment(cfg)
    assert len(summary["runs"]) == 1
    run_dir = next((tmp_path).glob("session_*/communication/run_1"))
    rounds = [
        json.loads(line)
        for line in (run_dir / "rounds.jsonl").read_text().splitlines()
        if line.strip() and not line.startswith("#")
    ]
    recs = [r for r in rounds if "churn" in r]
    assert recs and all(r["churn"] is not None for r in recs)
