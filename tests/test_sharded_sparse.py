"""Node-sharded sparse solver: bit-parity with the single-chip sparse
solver at tp=4 (noise off, balance 0 — exact integer arithmetic), plus
never-worse under the full objective and the guard rails."""

import numpy as np
import jax
import pytest

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.parallel import make_mesh
from kubernetes_rescheduling_tpu.parallel.sharded_sparse import (
    sharded_sparse_assign,
)
from kubernetes_rescheduling_tpu.solver import (
    GlobalSolverConfig,
    global_assign_sparse,
)


def _scn(n_pods=1024, n_nodes=16, seed=12):
    scn = synthetic_scenario(
        n_pods=n_pods, n_nodes=n_nodes, powerlaw=True, seed=seed,
        node_cpu_cap_m=8_000.0,
    )
    sg = sparsegraph.from_comm_graph(scn.graph)
    return scn, sg


@pytest.mark.slow  # tp↔single-chip sparse bit parity stays pinned fast by test_sparse_dp_of_tp_restarts_decision_parity below: composed dp-of-tp solves must equal dp-only single-chip solves bit-for-bit, which transits this exact tp route — this is the direct-comparison redundant variant (own ~27 s compile)
def test_bit_parity_with_single_chip_sparse():
    scn, sg = _scn()
    assert sg.num_blocks > 1
    cfg = GlobalSolverConfig(sweeps=3, noise_temp=0.0, balance_weight=0.0)
    key = jax.random.PRNGKey(5)
    st_single, info_single = global_assign_sparse(scn.state, sg, key, cfg)
    mesh = make_mesh(8, shape=(2, 4))  # dp=2 unused here, tp=4
    st_shard, info_shard = sharded_sparse_assign(scn.state, sg, key, mesh, cfg)
    np.testing.assert_array_equal(
        np.asarray(st_single.pod_node), np.asarray(st_shard.pod_node)
    )
    assert float(info_single["objective_after"]) == pytest.approx(
        float(info_shard["objective_after"]), rel=1e-6
    )
    assert int(info_shard["tp"]) == 4


@pytest.mark.slow  # tier-1 keeps sharded-sparse bit parity via
# test_sparse_dp_of_tp_restarts_decision_parity below (the composed
# route transits the same tp path) and hub coverage via
# test_sparse_solver's hub-blocks test
def test_bit_parity_with_hub_groups():
    # star services force hub blocks → the hub-group pass must stay in
    # lockstep with the single-chip path too
    S = 1024
    rng = np.random.default_rng(3)
    star_src = np.concatenate(
        [np.zeros(600, dtype=np.int64), np.ones(500, dtype=np.int64)]
    )
    star_dst = np.concatenate(
        [np.arange(2, 602, dtype=np.int64), np.arange(300, 800, dtype=np.int64)]
    )
    bg = rng.integers(0, S, size=(2, 1500))
    # reg_tiles=1 (512-wide regular blocks): the 600-neighbor star must
    # overflow into a hub block (at the default width no S=1024 block can)
    sg = sparsegraph.from_edges(
        np.concatenate([star_src, bg[0]]),
        np.concatenate([star_dst, bg[1]]),
        np.ones(len(star_src) + 1500),
        S,
        reg_tiles=1,
    )
    assert sg.hub_blocks
    scn = synthetic_scenario(
        n_pods=S, n_nodes=16, powerlaw=True, seed=9, node_cpu_cap_m=8_000.0
    )
    cfg = GlobalSolverConfig(sweeps=3, noise_temp=0.0, balance_weight=0.0)
    key = jax.random.PRNGKey(6)
    st_single, _ = global_assign_sparse(scn.state, sg, key, cfg)
    mesh = make_mesh(8, shape=(2, 4))
    st_shard, _ = sharded_sparse_assign(scn.state, sg, key, mesh, cfg)
    np.testing.assert_array_equal(
        np.asarray(st_single.pod_node), np.asarray(st_shard.pod_node)
    )


@pytest.mark.slow  # never-worse stays pinned fast by test_sparse_solver's
# test_sparse_solver_never_worse_and_improves
def test_never_worse_with_full_objective():
    scn, sg = _scn(seed=4)
    mesh = make_mesh(8, shape=(1, 8))
    # with the balance term active the guarantee is on the OBJECTIVE
    # (comm alone may rise while std falls — same contract as the dense
    # solvers)
    st, info = sharded_sparse_assign(
        scn.state, sg, jax.random.PRNGKey(1), mesh,
        GlobalSolverConfig(sweeps=4, balance_weight=0.5),
    )
    assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-4
    # with balance off, the objective IS comm — comm never worse
    before = float(communication_cost(scn.state, scn.graph))
    st0, info0 = sharded_sparse_assign(
        scn.state, sg, jax.random.PRNGKey(1), mesh,
        GlobalSolverConfig(sweeps=4, balance_weight=0.0),
    )
    assert float(communication_cost(st0, scn.graph)) <= before


def test_guards():
    scn, sg = _scn(n_pods=512, n_nodes=12, seed=2)
    mesh = make_mesh(8, shape=(1, 8))
    with pytest.raises(ValueError, match="multiple of tp"):
        sharded_sparse_assign(
            scn.state, sg, jax.random.PRNGKey(0), mesh, GlobalSolverConfig()
        )
    mesh4 = make_mesh(8, shape=(2, 4))
    # single-block graph → dense territory
    tiny = synthetic_scenario(n_pods=100, n_nodes=4, seed=1)
    sg_tiny = sparsegraph.from_comm_graph(tiny.graph)
    assert sg_tiny.num_blocks == 1
    with pytest.raises(ValueError, match="single-block"):
        sharded_sparse_assign(
            tiny.state, sg_tiny, jax.random.PRNGKey(0), mesh4,
            GlobalSolverConfig(),
        )


def test_move_cost_parity_and_gate():
    """Disruption pricing in the sharded sparse solver: bit-parity with
    the single-chip sparse solver at tp=4 (integer arithmetic), and the
    adopt gate covers the restart bill."""
    scn, sg = _scn(seed=8)
    cfg = GlobalSolverConfig(
        sweeps=3, noise_temp=0.0, balance_weight=0.0, move_cost=2.0
    )
    key = jax.random.PRNGKey(7)
    st_single, info_s = global_assign_sparse(scn.state, sg, key, cfg)
    mesh = make_mesh(8, shape=(2, 4))
    st_shard, info_h = sharded_sparse_assign(scn.state, sg, key, mesh, cfg)
    np.testing.assert_array_equal(
        np.asarray(st_single.pod_node), np.asarray(st_shard.pod_node)
    )
    if bool(info_h["improved"]):
        gain = float(info_h["objective_before"]) - float(info_h["objective_after"])
        assert gain > float(info_h["move_penalty"])


@pytest.mark.slow  # dp/tp routing + restart composition stays pinned fast
# by test_sparse_dp_of_tp_restarts_decision_parity below (which asserts the
# tp route, the restart count, and full decision parity)
def test_sparse_restarts_through_production_entry():
    """solve_with_restarts(sparse_graph=...) runs dp restarts of sparse
    solves (never worse than the best single restart) and routes tp>1 to
    the node-sharded sparse solver."""
    from kubernetes_rescheduling_tpu.parallel import solve_with_restarts

    scn, sg = _scn(seed=3)
    cfg = GlobalSolverConfig(sweeps=3, balance_weight=0.0)
    single, s_info = solve_with_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(4), config=cfg,
        sparse_graph=sg,
    )
    multi, m_info = solve_with_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(4), n_restarts=2,
        config=cfg, sparse_graph=sg,
    )
    assert int(m_info["restarts"]) == 2
    assert len(m_info["restart_objectives"]) == 2
    # best-of-2 never worse than restart 0 (the single solve's key stream
    # differs from restart keys, so compare within the multi run)
    assert float(m_info["objective_after"]) <= float(
        min(m_info["restart_objectives"])
    ) + 1e-4
    # tp route
    tp_state, tp_info = solve_with_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(4), config=cfg, tp=4,
        sparse_graph=sg,
    )
    assert int(tp_info["tp"]) == 4


def test_sparse_dp_of_tp_restarts_decision_parity():
    """The composed sparse path — dp restarts OF tp-sharded sparse solves
    — makes the same decisions as dp-only restarts of single-chip sparse
    solves (noise off): same per-restart key streams, bit-parity solves,
    same gated best-of-N selection."""
    from kubernetes_rescheduling_tpu.parallel import solve_with_restarts

    scn, sg = _scn(seed=3)
    cfg = GlobalSolverConfig(sweeps=3, balance_weight=0.0, noise_temp=0.0)
    dp_only, dp_info = solve_with_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(4), n_restarts=2,
        config=cfg, sparse_graph=sg,
    )
    composed, c_info = solve_with_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(4), n_restarts=2,
        config=cfg, tp=4, sparse_graph=sg,
    )
    assert int(c_info["tp"]) == 4 and int(c_info["restarts"]) == 2
    np.testing.assert_array_equal(
        np.asarray(dp_only.pod_node), np.asarray(composed.pod_node)
    )
    np.testing.assert_allclose(
        np.asarray(dp_info["restart_objectives"]),
        np.asarray(c_info["restart_objectives"]),
        rtol=1e-6,
    )
    assert int(dp_info["best_restart"]) == int(c_info["best_restart"])
