"""SLO v2: the bounded history plane (``telemetry.timeseries``), the
error-budget / burn-rate engine (``telemetry.slo``), their watchdog and
ops-plane wiring, and the ``/slo`` + ``/query`` surfaces.

The acceptance pins live here too: the fast burn rule flips /healthz
strictly EARLIER than the PR-18 ``serving_p99`` threshold rule on a
seeded overload; clean soaks finish with zero burn alerts, a full
budget, and registry output bit-identical (modulo the new families) to
an ``[slo]``-disabled run; and the store stays T-independent across a
1k-tenant feed with counted evictions."""

import json
import math
import urllib.error
import urllib.request

import pytest

from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.bench.loadgen import open_loop_arrivals
from kubernetes_rescheduling_tpu.bench.serve import run_serve_soak
from kubernetes_rescheduling_tpu.config import (
    ObsConfig,
    RescheduleConfig,
    ServingConfig,
    SloConfig,
)
from kubernetes_rescheduling_tpu.serving import ServingEngine
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import TenantSeries
from kubernetes_rescheduling_tpu.telemetry.server import OpsPlane
from kubernetes_rescheduling_tpu.telemetry.slo import (
    RULE_FAST_BURN,
    RULE_SLOW_BURN,
    SloEngine,
    SloSpec,
    budget_burn_frac,
    default_specs,
)
from kubernetes_rescheduling_tpu.telemetry.timeseries import (
    SeriesStore,
    series_key,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import SLORules, Watchdog


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _metric(registry, name, **labels):
    for rec in registry.snapshot():
        if rec["metric"] == name and (rec.get("labels") or {}) == labels:
            return rec.get("value")
    return None


def _counter_rec(metric, value, **labels):
    return {
        "metric": metric, "type": "counter", "labels": labels,
        "value": float(value),
    }


# ---------------- config surface ----------------


def test_slo_config_validation():
    SloConfig().validate()
    SloConfig(enabled=True).validate()
    with pytest.raises(ValueError):
        SloConfig(objective=1.0).validate()
    with pytest.raises(ValueError):
        SloConfig(objective=0.0).validate()
    with pytest.raises(ValueError):
        SloConfig(fast_window=1).validate()
    with pytest.raises(ValueError):
        SloConfig(fast_window=300, slow_window=288).validate()
    with pytest.raises(ValueError):
        SloConfig(budget_window=100, slow_window=288).validate()
    with pytest.raises(ValueError):
        SloConfig(fast_burn=-1.0).validate()
    with pytest.raises(ValueError):
        SloConfig(series_capacity=1).validate()
    with pytest.raises(ValueError):
        SloConfig(max_series=0).validate()


def test_slo_config_from_toml(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "max_rounds = 2\n"
        "[slo]\n"
        "enabled = true\n"
        "objective = 0.95\n"
        "latency_threshold_ms = 25.0\n"
        "fast_window = 24\n"
        "fast_burn = 10.0\n"
        "slow_window = 96\n"
        "budget_window = 256\n"
        "max_series = 64\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.slo.enabled
    assert cfg.slo.objective == 0.95
    assert cfg.slo.latency_threshold_ms == 25.0
    assert cfg.slo.fast_window == 24
    assert cfg.slo.fast_burn == 10.0
    assert cfg.slo.slow_window == 96
    assert cfg.slo.budget_window == 256
    assert cfg.slo.max_series == 64
    cfg.validate()


# ---------------- SeriesStore ----------------


def test_series_key_sorts_labels():
    assert series_key("m", {"b": "2", "a": "1"}) == 'm{a="1",b="2"}'
    assert series_key("m", None) == "m"
    assert series_key("m", {"a": "1"}, part="sum") == 'm:sum{a="1"}'


def test_ring_capacity_bounds_points(registry):
    store = SeriesStore(capacity=4, max_series=8, families=None)
    for t in range(1, 11):
        store.record("m", {}, t, float(t))
    pts = store.query("m")
    assert len(pts) == 4
    assert pts == [(7, 7.0), (8, 8.0), (9, 9.0), (10, 10.0)]


def test_series_budget_evicts_lru_counted(registry):
    store = SeriesStore(
        capacity=8, max_series=2, families=None, registry=registry
    )
    store.sample([_counter_rec("a_total", 1)], 1)
    store.sample([_counter_rec("b_total", 1)], 2)
    # touching a_total makes b_total the LRU victim
    store.sample([_counter_rec("a_total", 2)], 3)
    store.sample([_counter_rec("c_total", 1)], 4)
    assert store.evictions == 1
    assert set(store.names()) == {"a_total", "c_total"}
    assert _metric(registry, "timeseries_evictions_total") == 1
    assert _metric(registry, "timeseries_series") == 2
    with pytest.raises(KeyError):
        store.query("b_total")


def test_delta_is_reset_tolerant(registry):
    store = SeriesStore(capacity=8, max_series=4, families=None)
    for t, v in ((1, 10.0), (2, 20.0), (3, 5.0)):
        store.record("m", {}, t, v)
    # 10 -> 20 is +10; the drop to 5 is a restart, so 5 IS the delta
    assert store.delta("m", 100, now=3) == pytest.approx(15.0)
    assert store.delta("missing", 100) == 0.0


def test_delta_window_predating_ring_attributes_first_point(registry):
    store = SeriesStore(capacity=2, max_series=4, families=None)
    for t in range(1, 6):
        store.record("m", {}, t, 10.0 * t)
    # the ring holds (4, 40), (5, 50); a window reaching the ring's edge
    # attributes the first retained point's full value (capacity-bounded
    # honesty) plus the observed increase
    assert store.delta("m", 2, now=5) == pytest.approx(50.0)
    # a window inside the ring sees only the observed increase
    assert store.delta("m", 1, now=5) == pytest.approx(10.0)


def test_family_allowlist_filters(registry):
    store = SeriesStore(capacity=4, max_series=8, families=("kept_total",))
    store.sample(
        [_counter_rec("kept_total", 1), _counter_rec("dropped_total", 1)], 1
    )
    assert store.names() == ["kept_total"]


def test_histogram_sampling_parts(registry):
    store = SeriesStore(
        capacity=4, max_series=16, families=("h",), bucket_families=("h",)
    )
    store.sample(
        [{
            "metric": "h", "type": "histogram", "labels": {"stage": "total"},
            "count": 10, "sum": 0.5,
            "buckets": {"0.001": 4, "0.01": 3, "0.1": 2}, "inf": 1,
        }],
        1,
    )
    key = 'h{stage="total"}'
    assert store.value(key) == 10.0  # bare name carries the count
    assert store.value('h:sum{stage="total"}') == 0.5
    # bucket series are CUMULATIVE counts per upper bound
    assert store.value('h:le:0.001{stage="total"}') == 4.0
    assert store.value('h:le:0.01{stage="total"}') == 7.0
    assert store.value('h:le:0.1{stage="total"}') == 9.0


def test_store_is_T_independent_across_1k_tenants(registry):
    """The acceptance memory pin: a 1k-tenant feed holds the same bytes
    as a solo run — series and points bounded by the configured budgets,
    the overflow counted as evictions."""
    store = SeriesStore(
        capacity=32, max_series=16, families=None, registry=registry
    )
    for tick in range(1, 4):
        store.sample(
            [
                _counter_rec("fleet_moves_total", tick, tenant=f"t{i}")
                for i in range(1000)
            ],
            tick,
        )
    assert len(store) == 16
    assert store.points() <= 16 * 32
    assert store.evictions >= 1000 - 16
    assert _metric(registry, "timeseries_evictions_total") == store.evictions
    assert _metric(registry, "timeseries_series") == 16.0


def test_query_last_n_and_bare_listing(registry):
    store = SeriesStore(capacity=8, max_series=4, families=None)
    for t in range(1, 6):
        store.record("m", {}, t, float(t))
    assert store.query("m", n=2) == [(4, 4.0), (5, 5.0)]
    assert store.query("m", n=0) == []


# ---------------- SloSpec / SloEngine ----------------


def test_slo_spec_validation():
    with pytest.raises(ValueError):
        SloSpec(name="").validate()
    with pytest.raises(ValueError):
        SloSpec(name="x", objective=1.5).validate()
    with pytest.raises(ValueError):
        SloSpec(name="x", kind="events").validate()  # no selectors
    with pytest.raises(ValueError):
        SloSpec(name="x", kind="latency", family="h").validate()  # no thresh
    with pytest.raises(ValueError):
        SloSpec(name="x", kind="nope", good=(("a", ()),)).validate()


def test_default_specs_latency_spec_is_gated():
    names = {s.name for s in default_specs()}
    assert names == {"serving_availability", "rounds_success"}
    names = {s.name for s in default_specs(latency_threshold_ms=20.0)}
    assert "serving_latency" in names


def test_budget_burn_frac_math():
    assert budget_burn_frac(970, 30, 0.99) == pytest.approx(3.0)
    assert budget_burn_frac(100, 0, 0.99) == 0.0
    assert budget_burn_frac(0, 0, 0.99) == 0.0
    assert budget_burn_frac(0, 5, 0.99) == pytest.approx(100.0)


def _events_engine(registry, **kw):
    store = SeriesStore(
        capacity=64, max_series=16, families=None, registry=registry
    )
    spec = SloSpec(
        name="t", objective=kw.pop("objective", 0.9),
        good=(("ok_total", ()),), bad=(("bad_total", ()),),
    )
    engine = SloEngine((spec,), store, registry=registry, **kw)
    return store, engine


def test_burn_rate_and_budget_math(registry):
    store, engine = _events_engine(
        registry, budget_window=16, fast_window=4, slow_window=8
    )
    # steady 20% bad: burn = 0.2 / (1 - 0.9) = 2.0
    for tick in range(1, 9):
        store.sample(
            [
                _counter_rec("ok_total", 8 * tick),
                _counter_rec("bad_total", 2 * tick),
            ],
            tick,
        )
    spec = engine.specs[0]
    assert engine.burn_rate(spec, 4) == pytest.approx(2.0)
    entries = engine.evaluate(8)
    # default thresholds (14.4 / 6.0) are above a 2.0 burn: no entries,
    # but the table and gauges carry the budget state
    assert entries == {}
    row = engine.table()[0]
    assert row["slo"] == "t"
    assert row["burn_fast"] == pytest.approx(2.0)
    assert row["budget_remaining_frac"] == 0.0  # 20% bad vs 10% allowed
    assert _metric(registry, "slo_budget_remaining_frac", slo="t") == 0.0
    assert _metric(
        registry, "slo_burn_rate", slo="t", window="fast"
    ) == pytest.approx(2.0)
    assert _metric(
        registry, "slo_burn_rate", slo="t", window="slow"
    ) == pytest.approx(2.0)


def test_burn_entries_fire_over_threshold(registry):
    store, engine = _events_engine(
        registry, budget_window=16, fast_window=4, fast_burn=1.5,
        slow_window=8, slow_burn=1.2,
    )
    for tick in range(1, 9):
        store.sample(
            [
                _counter_rec("ok_total", 8 * tick),
                _counter_rec("bad_total", 2 * tick),
            ],
            tick,
        )
    entries = engine.evaluate(8)
    assert set(entries) == {RULE_FAST_BURN, RULE_SLOW_BURN}
    fast = entries[RULE_FAST_BURN]
    assert fast["slo"] == "t"
    assert fast["burn_rate"] == pytest.approx(2.0)
    assert fast["window"] == 4
    assert fast["short_window"] == 1
    assert fast["threshold"] == 1.5
    assert fast["value"] == fast["burn_rate"]
    assert 0.0 <= fast["budget_remaining_frac"] <= 1.0
    assert fast["time_to_exhaustion"] is not None


def test_multi_window_confirm_kills_stale_spike(registry):
    """The multi-window trick: a burn that already drained must not
    page. Bad events through tick 11, a clean tick 12 — the long fast
    window still reads hot, but the 1-tick confirm window is clean."""
    store, engine = _events_engine(
        registry, budget_window=24, fast_window=12, fast_burn=1.5,
        slow_window=20, slow_burn=1e9,  # isolate the fast pair
    )
    for tick in range(1, 12):
        store.sample(
            [
                _counter_rec("ok_total", 5 * tick),
                _counter_rec("bad_total", 5 * tick),
            ],
            tick,
        )
    store.sample(
        [_counter_rec("ok_total", 75), _counter_rec("bad_total", 55)], 12
    )
    spec = engine.specs[0]
    assert engine.burn_rate(spec, 12) > 1.5  # long window still hot
    assert engine.burn_rate(spec, 1) == 0.0  # confirm window clean
    assert engine.evaluate(12) == {}


def test_latency_mode_events_from_histogram(registry):
    store = SeriesStore(
        capacity=16, max_series=16, families=("h",), bucket_families=("h",),
        registry=registry,
    )
    spec = SloSpec(
        name="lat", objective=0.9, kind="latency", family="h",
        labels=(("stage", "total"),), threshold_s=0.01,
    )
    engine = SloEngine(
        (spec,), store, registry=registry,
        budget_window=8, fast_window=4, fast_burn=1.5, slow_window=6,
        slow_burn=1e9,
    )
    # tick 1: 10 requests, 9 under 10ms; tick 2: +10, only 2 more under
    # -> window-2 events: good 11, bad 9 (burn = 0.45 / 0.1 = 4.5)
    for tick, (c, under) in enumerate(((10, 9), (20, 11)), start=1):
        store.sample(
            [{
                "metric": "h", "type": "histogram",
                "labels": {"stage": "total"}, "count": c, "sum": 0.1,
                "buckets": {"0.001": under // 2, "0.01": under - under // 2,
                            "0.1": c - under},
                "inf": 0,
            }],
            tick,
        )
    good, bad = engine._events(spec, 2)
    assert good == pytest.approx(11.0)
    assert bad == pytest.approx(9.0)
    entries = engine.evaluate(2)
    assert RULE_FAST_BURN in entries


def test_tenant_gate_enabled_accumulates_and_publishes(registry):
    store, engine = _events_engine(registry)
    engine.tenant_series = TenantSeries(registry, tenants=2, budget=4)
    engine.observe_tenant_round("a", ok=True)
    engine.observe_tenant_round("a", ok=False)
    engine.observe_tenant_round("b", ok=True)
    budgets = engine.tenant_budgets()
    # objective 0.9: 1 bad of 2 rounds is 5x the allowance -> exhausted
    assert budgets["a"] == 0.0
    assert budgets["b"] == 1.0
    assert _metric(
        registry, "slo_tenant_budget_remaining_frac", tenant="a"
    ) == 0.0
    assert _metric(
        registry, "slo_tenant_budget_remaining_frac", tenant="b"
    ) == 1.0


def test_tenant_gate_over_budget_suppresses_counted(registry):
    store, engine = _events_engine(registry)
    engine.tenant_series = TenantSeries(registry, tenants=5, budget=2)
    for i in range(5):
        engine.observe_tenant_round(f"t{i}", ok=False)
    # nothing stored, nothing labeled — the gate counts each suppression
    assert engine._tenant_events == {}
    assert (
        _metric(registry, "slo_tenant_budget_remaining_frac", tenant="t0")
        is None
    )
    assert _metric(
        registry,
        "tenant_series_suppressed_total",
        family="slo_tenant_budget_remaining_frac",
    ) == 5.0


# ---------------- watchdog integration ----------------


def _burn_detail(**over):
    detail = {
        "slo": "t", "burn_rate": 20.0, "burn_rate_short": 20.0,
        "window": 12, "short_window": 1, "threshold": 14.4,
        "budget_remaining_frac": 0.4, "time_to_exhaustion": 9.0,
        "value": 20.0,
    }
    detail.update(over)
    return detail


def test_watchdog_burn_entry_recovery_and_rebase(registry):
    wd = Watchdog(SLORules(), registry=registry)
    raised = wd.observe_slo_burn({RULE_FAST_BURN: _burn_detail()})
    assert [v["rule"] for v in raised] == [RULE_FAST_BURN]
    assert _metric(
        registry, "slo_violations_total", rule=RULE_FAST_BURN
    ) == 1.0
    assert not wd.healthy
    # re-feeding the same entry is NOT a new violation
    assert wd.observe_slo_burn({RULE_FAST_BURN: _burn_detail()}) == []
    # the burn draining recovers the rule
    assert wd.observe_slo_burn({}) == []
    assert wd.healthy
    # rebase clears latched burn state: a new run starts clean
    wd.observe_slo_burn({RULE_FAST_BURN: _burn_detail()})
    wd.rebase()
    wd.check()
    assert wd.healthy


def test_uniform_verdict_shape_across_rule_kinds(registry):
    """Satellite pin: every active /healthz verdict — burn-rate and
    legacy threshold rules alike — carries the uniform
    {rule, value, threshold, since} quartet, while rule-specific detail
    keys (the old test pins) survive."""
    wd = Watchdog(
        SLORules(serving_p99_ms=50.0, min_samples=2), registry=registry
    )
    wd.observe_serving(
        {"count": 8, "p99_ms": 120.0, "p50_ms": 60.0, "rate_rps": 10.0}
    )
    wd.observe_slo_burn({RULE_FAST_BURN: _burn_detail()})
    status = wd.status()
    assert not status["healthy"]
    active = {v["rule"]: v for v in status["active"]}
    assert set(active) == {"serving_p99", RULE_FAST_BURN}
    for verdict in active.values():
        assert isinstance(verdict["value"], float)
        assert isinstance(verdict["threshold"], float)
        assert verdict["since"] > 0
    # legacy detail keys retained alongside the quartet
    assert active["serving_p99"]["threshold_ms"] == 50.0
    assert active["serving_p99"]["value"] == 120.0
    assert active["serving_p99"]["threshold"] == 50.0
    assert active[RULE_FAST_BURN]["value"] == 20.0
    assert active[RULE_FAST_BURN]["threshold"] == 14.4
    # `since` is stable while the violation persists...
    first_since = active[RULE_FAST_BURN]["since"]
    wd.observe_slo_burn({RULE_FAST_BURN: _burn_detail(burn_rate=21.0)})
    again = {v["rule"]: v for v in wd.status()["active"]}
    assert again[RULE_FAST_BURN]["since"] == first_since
    # ...and resets across a recovery
    wd.observe_slo_burn({})
    wd.observe_slo_burn({RULE_FAST_BURN: _burn_detail()})
    final = {v["rule"]: v for v in wd.status()["active"]}
    assert final[RULE_FAST_BURN]["since"] >= first_since


# ---------------- ops plane + endpoints ----------------


def _summary(count, p99_ms):
    return {
        "submitted": count, "completed": count, "count": count,
        "rate_rps": 10.0, "p50_ms": p99_ms / 2, "p95_ms": p99_ms,
        "p99_ms": p99_ms, "batch_sizes": {"1": count}, "dispatches": count,
        "outcomes": {"placed": count}, "shed": {}, "inflight": 0,
    }


def _feed_outcomes(registry, placed=0, shed=0):
    c = registry.counter(
        "serving_placements_total",
        "serving requests completed by outcome",
        labelnames=("outcome",),
    )
    if placed:
        c.labels(outcome="placed").inc(placed)
    if shed:
        c.labels(outcome="shed").inc(shed)


def test_slo_and_query_endpoints_roundtrip(registry):
    obs = ObsConfig(serve_port=0).validate()
    slo = SloConfig(
        enabled=True, fast_window=12, slow_window=24, budget_window=48
    ).validate()
    ops = OpsPlane.from_config(obs, slo=slo, registry=registry).start()
    try:
        port = ops.server.port
        for tick in range(1, 4):
            _feed_outcomes(registry, placed=10)
            ops.observe_serving(_summary(count=10, p99_ms=5.0))
        status, body = _get(port, "/slo")
        assert status == 200
        table = {row["slo"]: row for row in json.loads(body)["slos"]}
        assert table["serving_availability"]["budget_remaining_frac"] == 1.0
        assert table["serving_availability"]["burn_fast"] == 0.0
        status, body = _get(port, "/query")
        assert status == 200
        names = json.loads(body)["series"]
        assert 'serving_placements_total{outcome="placed"}' in names
        status, body = _get(
            port, '/query?series=serving_placements_total'
            '%7Boutcome%3D%22placed%22%7D&n=2'
        )
        assert status == 200
        doc = json.loads(body)
        assert doc["points"] == [[2, 20.0], [3, 30.0]]
        status, body = _get(port, "/query?series=nope_total")
        assert status == 404
        assert "unknown series" in json.loads(body)["error"]
    finally:
        ops.close()


def test_slo_endpoints_404_when_plane_disabled(registry):
    obs = ObsConfig(serve_port=0).validate()
    ops = OpsPlane.from_config(obs, registry=registry).start()
    try:
        port = ops.server.port
        for path in ("/slo", "/query"):
            status, body = _get(port, path)
            assert status == 404
            assert "slo plane disabled" in json.loads(body)["error"]
    finally:
        ops.close()


def test_fast_burn_flips_healthz_before_serving_p99(registry, tmp_path):
    """THE acceptance ordering pin: on a seeded overload the fast burn
    rule pages (503 + structured slo stanza + slo_burn_page bundle)
    strictly earlier than the PR-18 serving_p99 threshold rule — budget
    math detects 'the tail will be blown' before the tail is blown."""
    obs = ObsConfig(
        serve_port=0, slo_serving_p99_ms=50.0, slo_min_samples=5
    ).validate()
    slo = SloConfig(
        enabled=True, fast_window=12, slow_window=24, budget_window=48
    ).validate()
    ops = OpsPlane.from_config(
        obs, slo=slo, registry=registry, bundle_dir=str(tmp_path)
    ).start()
    first_burn = first_p99 = None
    try:
        port = ops.server.port
        for tick in range(1, 11):
            # a steady 20% shed rate from the first tick; p99 ramps and
            # crosses the 50 ms threshold only at tick 6
            _feed_outcomes(registry, placed=8, shed=2)
            ops.observe_serving(_summary(count=20, p99_ms=10.0 * tick))
            status, body = _get(port, "/healthz")
            active = {
                v["rule"]: v
                for v in (json.loads(body)["slo"] or {}).get("active", [])
            }
            if first_burn is None and RULE_FAST_BURN in active:
                first_burn = tick
                assert status == 503
                # the structured stanza: budget remaining, burn rate,
                # window, time-to-exhaustion, and the uniform quartet
                stanza = active[RULE_FAST_BURN]
                assert stanza["slo"] == "serving_availability"
                assert stanza["burn_rate"] >= 14.4
                assert stanza["window"] == 12
                assert "budget_remaining_frac" in stanza
                assert "time_to_exhaustion" in stanza
                assert stanza["value"] == stanza["burn_rate"]
                assert stanza["threshold"] == 14.4
                assert stanza["since"] > 0
            if first_p99 is None and "serving_p99" in active:
                first_p99 = tick
        assert first_burn is not None, "fast burn never fired"
        assert first_p99 is not None, "serving_p99 never fired"
        assert first_burn < first_p99, (
            f"burn paged at tick {first_burn}, not strictly before "
            f"serving_p99 at tick {first_p99}"
        )
        # page-level entry dumped a flight-recorder bundle, exactly once
        bundles = list(tmp_path.glob("*slo_burn_page*"))
        assert len(bundles) == 1
        payload = json.loads(bundles[0].read_text())
        assert payload["slo"]["rule"] == RULE_FAST_BURN
        assert any(
            row["slo"] == "serving_availability" for row in payload["table"]
        )
    finally:
        ops.close()


def _strip_slo_families(text):
    """Drop the SLO v2 families (gauge samples + HELP/TYPE) from an
    exposition — what's left must be bit-identical to a run with the
    [slo] block disabled."""
    out = []
    for line in text.splitlines(keepends=True):
        name = line.split()[2] if line.startswith("#") else line
        if name.startswith(("slo_", "timeseries_")):
            continue
        out.append(line)
    return "".join(out)


def test_clean_soak_full_budget_and_bit_identical_registry():
    """Acceptance: a clean soak finishes with zero burn alerts, a full
    budget on every SLO, and — modulo the new slo_*/timeseries_*
    families — registry output bit-identical to an [slo]-disabled run."""
    obs = ObsConfig(serve_port=None).validate()
    reg_on, reg_off = MetricsRegistry(), MetricsRegistry()
    slo = SloConfig(
        enabled=True, fast_window=12, slow_window=24, budget_window=48
    ).validate()
    ops_on = OpsPlane.from_config(obs, slo=slo, registry=reg_on)
    ops_off = OpsPlane.from_config(obs, registry=reg_off)
    for tick in range(1, 21):
        for reg, ops in ((reg_on, ops_on), (reg_off, ops_off)):
            _feed_outcomes(reg, placed=5)
            ops.observe_serving(_summary(count=10, p99_ms=3.0))
    assert ops_on.watchdog.active == {}
    assert ops_off.watchdog.active == {}
    for row in ops_on.slo_engine.table():
        assert row["budget_remaining_frac"] == 1.0
        assert row["burn_fast"] == 0.0
        assert row["burn_slow"] == 0.0
    assert _strip_slo_families(reg_on.expose()) == reg_off.expose()


def test_plane_ticks_on_round_and_rollup_feeds(registry):
    obs = ObsConfig(serve_port=None).validate()
    slo = SloConfig(enabled=True).validate()
    ops = OpsPlane.from_config(obs, slo=slo, registry=registry)

    class _Rec:
        degraded = False
        round = 1
        decision_latency_s = 0.01
        communication_cost = 5.0

        def as_dict(self):
            return {"round": 1}

    ops.observe_round(_Rec())
    assert ops.series_store.last_tick == 1
    ops.observe_fleet_rollup(
        {"dims": {"cost": {"quantiles": {"p99": 10.0}}}}
    )
    assert ops.series_store.last_tick == 2
    assert len(ops.slo_engine.table()) == len(ops.slo_engine.specs)


def test_bind_tenant_series_routes_per_tenant_budgets(registry):
    obs = ObsConfig(serve_port=None).validate()
    slo = SloConfig(enabled=True).validate()
    ops = OpsPlane.from_config(obs, slo=slo, registry=registry)
    ops.bind_tenant_series(TenantSeries(registry, tenants=2, budget=4))
    ops.observe_tenant("a", record={"degraded": False})
    ops.observe_tenant("a", skipped=True)
    ops.observe_tenant("b", record={"degraded": True})
    budgets = ops.slo_engine.tenant_budgets()
    assert budgets["a"] < 1.0  # the skip burned budget
    assert budgets["b"] == 0.0  # degraded round counts as bad
    assert (
        _metric(registry, "slo_tenant_budget_remaining_frac", tenant="a")
        is not None
    )
    # slo plane off: bind is a silent no-op
    ops_off = OpsPlane.from_config(obs, registry=registry)
    ops_off.bind_tenant_series(TenantSeries(registry, tenants=2, budget=4))
    ops_off.observe_tenant("a", record={})


# ---------------- real-engine burn soak ----------------


def _overload_soak(registry, ops, n, rate):
    backend = make_backend("mubench", 0)
    engine = ServingEngine(
        backend,
        registry=registry,
        config=ServingConfig(max_batch=2, queue_depth=2, deadline_ms=2.0),
    )
    ops.bind_serving(engine)
    services = list(engine.graph.names)
    with engine:
        report = run_serve_soak(
            engine,
            services,
            open_loop_arrivals(rate, n, seed=1),
            deadline_ms=2.0,
        )
    return report


def test_acceptance_burn_soak_fast(registry, tmp_path):
    """Tier-1 burn-detection soak: a REAL serving engine under seeded
    overload (tiny queue, tight deadline, hot open-loop rate) drives the
    history plane through its own ops feeds and trips the fast burn
    page — counted violation, slo_burn_page bundle, live /slo table."""
    obs = ObsConfig(serve_port=0).validate()
    slo = SloConfig(
        enabled=True, objective=0.9, fast_window=12, fast_burn=2.0,
        slow_window=24, slow_burn=1.5, budget_window=48,
    ).validate()
    ops = OpsPlane.from_config(
        obs, slo=slo, registry=registry, bundle_dir=str(tmp_path)
    ).start()
    try:
        report = _overload_soak(registry, ops, n=80, rate=3000.0)
        assert report["shed"] + report["timed_out"] > 0
        assert (
            _metric(registry, "slo_violations_total", rule=RULE_FAST_BURN)
            >= 1.0
        )
        assert list(tmp_path.glob("*slo_burn_page*"))
        status, body = _get(ops.server.port, "/slo")
        assert status == 200
        table = {row["slo"]: row for row in json.loads(body)["slos"]}
        # the budget may have RECOVERED by the end (the burst slides out
        # of the rolling window) — the live table just has to be there,
        # current, and honest about the window it read
        row = table["serving_availability"]
        assert row["tick"] == ops.series_store.last_tick
        assert row["budget_window"] == 48
        # the page itself carried the hot budget state: the bundle's
        # frozen table saw a drained budget even if the live one healed
        payload = json.loads(
            list(tmp_path.glob("*slo_burn_page*"))[0].read_text()
        )
        frozen = {r["slo"]: r for r in payload["table"]}
        assert frozen["serving_availability"]["budget_remaining_frac"] < 1.0
    finally:
        ops.close()


@pytest.mark.slow  # 300-request high-rate variant; burn detection stays pinned fast in tier-1 by test_acceptance_burn_soak_fast above
def test_burn_soak_long(registry, tmp_path):
    obs = ObsConfig(serve_port=None).validate()
    slo = SloConfig(
        enabled=True, objective=0.9, fast_window=12, fast_burn=2.0,
        slow_window=24, slow_burn=1.5, budget_window=96,
    ).validate()
    ops = OpsPlane.from_config(
        obs, slo=slo, registry=registry, bundle_dir=str(tmp_path)
    )
    report = _overload_soak(registry, ops, n=300, rate=4000.0)
    assert report["answered"] + report["shed"] + report["timed_out"] == 300
    assert (
        _metric(registry, "slo_violations_total", rule=RULE_FAST_BURN) >= 1.0
    )
    # the slow ticket rule catches the sustained leak too
    assert (
        _metric(registry, "slo_violations_total", rule=RULE_SLOW_BURN) >= 1.0
    )


@pytest.mark.slow  # clean-soak long variant; the zero-alert + bit-identical invariant stays pinned fast in tier-1 by test_clean_soak_full_budget_and_bit_identical_registry above
def test_clean_soak_long_zero_burn(registry):
    obs = ObsConfig(serve_port=None).validate()
    slo = SloConfig(enabled=True).validate()
    ops = OpsPlane.from_config(obs, slo=slo, registry=registry)
    for tick in range(1, 601):
        _feed_outcomes(registry, placed=5)
        ops.observe_serving(_summary(count=10, p99_ms=3.0))
    assert ops.watchdog.active == {}
    assert _metric(registry, "slo_violations_total", rule=RULE_FAST_BURN) is None
    for row in ops.slo_engine.table():
        assert row["budget_remaining_frac"] == 1.0


# ---------------- report + CLI surface ----------------


def test_report_slo_budget_table_and_sparklines(registry, tmp_path):
    from kubernetes_rescheduling_tpu.telemetry.report import report_slo

    obs = ObsConfig(serve_port=None).validate()
    slo = SloConfig(
        enabled=True, fast_window=12, slow_window=24, budget_window=48
    ).validate()
    ops = OpsPlane.from_config(obs, slo=slo, registry=registry)
    dump = tmp_path / "metrics.jsonl"
    for tick in range(1, 6):
        _feed_outcomes(registry, placed=8, shed=2)
        ops.observe_serving(_summary(count=20, p99_ms=5.0))
        registry.dump_jsonl(dump)
    out = report_slo([str(dump)])
    assert "slo                      budget" in out
    assert "serving_availability" in out
    assert "rounds_success" in out
    assert "burn serving_availability/fast:" in out
    spark_line = next(
        line for line in out.splitlines()
        if line.startswith("    burn serving_availability/fast:")
    )
    # a hot burn renders high glyphs, and the latest reading is printed
    assert "█" in spark_line
    assert "(last " in spark_line


def test_report_slo_events_and_empty_shapes(tmp_path):
    from kubernetes_rescheduling_tpu.telemetry.report import report_slo

    events = tmp_path / "events.jsonl"
    events.write_text(
        json.dumps({
            "event": "slo_violation", "rule": RULE_FAST_BURN,
            "slo": "serving_availability", "burn_rate": 20.0, "window": 12,
            "budget_remaining_frac": 0.4,
        }) + "\n"
        + json.dumps({"event": "slo_recovered", "rule": RULE_FAST_BURN})
        + "\n"
    )
    out = report_slo([str(events)])
    assert (
        "VIOLATION slo_fast_burn slo=serving_availability "
        "burn=20.0 over 12t (budget 40.0% left)" in out
    )
    assert "recovered slo_fast_burn" in out
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps({"event": "round"}) + "\n")
    assert "was this run started with --slo?" in report_slo([str(bare)])
    assert "not a file" in report_slo([str(tmp_path / "missing.jsonl")])


def test_cli_telemetry_slo_mode(registry, tmp_path, capsys):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    obs = ObsConfig(serve_port=None).validate()
    slo = SloConfig(enabled=True).validate()
    ops = OpsPlane.from_config(obs, slo=slo, registry=registry)
    _feed_outcomes(registry, placed=10)
    ops.observe_serving(_summary(count=10, p99_ms=3.0))
    dump = tmp_path / "metrics.jsonl"
    registry.dump_jsonl(dump)
    rc = cli_main(["telemetry", "slo", str(dump)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serving_availability" in out
    assert "100.00%" in out  # clean feed: full budget


def test_telemetry_report_serving_stanza(registry, tmp_path, capsys):
    """Satellite pin: `telemetry report` on a dump from a served run
    renders the serving stanza — outcome totals, latency percentiles,
    placements/sec (needs >= 2 ts-stamped snapshots), shed breakdown,
    and the batch-size distribution."""
    from kubernetes_rescheduling_tpu.cli import main as cli_main
    from kubernetes_rescheduling_tpu.telemetry.registry import MICRO_BUCKETS

    c = registry.counter(
        "serving_placements_total", "outcomes", labelnames=("outcome",)
    )
    c.labels(outcome="placed").inc(18)
    c.labels(outcome="shed").inc(2)
    registry.counter(
        "serving_shed_total", "sheds", labelnames=("reason",)
    ).labels(reason="queue_full").inc(2)
    h = registry.histogram(
        "serving_request_seconds", "latency", labelnames=("stage",),
        buckets=MICRO_BUCKETS,
    )
    for v in (0.001, 0.002, 0.004, 0.008):
        h.labels(stage="total").observe(v)
    registry.histogram(
        "serving_batch_size", "batch", buckets=(1.0, 2.0, 4.0, 8.0)
    ).observe(3)
    dump = tmp_path / "metrics.jsonl"
    registry.dump_jsonl(dump)
    import time

    time.sleep(0.05)
    h.labels(stage="total").observe(0.002)
    registry.dump_jsonl(dump)
    rc = cli_main(["telemetry", str(dump)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serving plane: placed=18 shed=2" in out
    assert "latency(total): p50=" in out
    assert "placements/sec: " in out
    assert "shed: queue_full×2" in out
    assert "batch sizes: " in out
