"""Fleet v2: the batched global-solver and forecast planes + lifted gates.

The invariants pinned here extend the PR-6 fleet contract to every newly
batched decision plane (ISSUE 15):

- the batched GLOBAL solve (``solver.fleet_global``) — restart fan-out
  included — makes per-tenant decisions BIT-EXACT with the solo
  ``solve_with_restarts`` path, in the solo loop's applied-move ORDER,
  on both device planes (vmap and the dp shard_map);
- the batched PROACTIVE plane: the stacked forecast RLS state
  (``forecast.fleet``) evolves bit-exactly with the solo jitted forecast
  kernel (including the per-tenant skill gate), and the predicted-state
  decide matches the solo proactive kernel, vmap AND dp;
- mask twins: a tenant padded to a shared fleet bucket and mask-threaded
  makes the SAME decisions as its unpadded solo run;
- one counted device transfer per fleet round survives on the new
  planes (loop-pinned per site, kernel-pinned at T=256);
- chaos isolation holds on the new planes: one tenant on fire leaves
  every other tenant's records bit-identical to a no-chaos run;
- solver-cache slots evict (counted) when churn rewrites a tenant's
  graph, so long deploy-waves soaks cannot accrete stale generations.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.fleet import FleetBackend, make_fleet
from kubernetes_rescheduling_tpu.bench.boundary import BoundaryClient
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.fleet import run_fleet_controller
from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.config import (
    ChaosConfig,
    ElasticConfig,
    FleetConfig,
    ForecastConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.forecast.fleet import (
    _fleet_forecast,
    init_fleet_forecast_state,
    repad_fleet_forecast_state,
)
from kubernetes_rescheduling_tpu.forecast.model import (
    forecast_step,
    init_forecast_state,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.solver.fleet import (
    fleet_solve_proactive,
    stack_tenants,
)
from kubernetes_rescheduling_tpu.solver.fleet_global import (
    decode_fleet_global,
    fleet_global_solve,
)
from kubernetes_rescheduling_tpu.solver.global_solver import GlobalSolverConfig
from kubernetes_rescheduling_tpu.solver.round_loop import decide_with_forecast
from kubernetes_rescheduling_tpu.parallel.sharded import solve_with_restarts
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    set_registry,
)
from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _mubench_fleet(n=3, seed=0):
    fleet = make_fleet("mubench", n, seed=seed)
    fleet.inject_imbalance()
    return fleet


def _stacked(fleet):
    states = [b.monitor() for b in fleet.backends]
    graphs = [b.comm_graph() for b in fleet.backends]
    return states, graphs, stack_tenants(states), stack_tenants(graphs)


def _keys(n, seed=0):
    return jnp.stack(
        [jax.random.fold_in(jax.random.PRNGKey(seed), t) for t in range(n)]
    )


def _solo_changed_moves(state, new_state):
    """The solo ``_global_round`` host loop's move extraction: changed
    services in first-moved-pod order — the ordering oracle the batched
    decode must reproduce."""
    old = np.asarray(state.pod_node)
    new = np.asarray(new_state.pod_node)
    valid = np.asarray(state.pod_valid)
    svc = np.asarray(state.pod_service)
    changed, seen = [], set()
    for i in np.flatnonzero(valid & (old != new)):
        s = int(svc[i])
        if s in seen:
            continue
        seen.add(s)
        changed.append((s, int(new[i])))
    return changed


# ---------------- batched global solve ----------------


@pytest.mark.parametrize("n_restarts", [
    1,
    pytest.param(2, marks=pytest.mark.slow),  # the batched-vs-solo
    # bit-exact pin stays fast in the n_restarts=1 case above, and the
    # restart fan-out stays fast in
    # test_fleet_global_dp_plane_matches_vmap_plane[2] (same scan+argmin
    # shard body); this case re-proves both with its own ~19 s compile
])
def test_fleet_global_solve_bit_exact_vs_solo(n_restarts):
    """ONE batched dispatch re-places every tenant's services with the
    solo solver's exact decisions — restart fan-out included (the scan +
    argmin composition is parallel_restarts' shard body verbatim)."""
    fleet = _mubench_fleet(3)
    states, graphs, st, gr = _stacked(fleet)
    cfg = GlobalSolverConfig(sweeps=3, balance_weight=0.5, move_cost=0.5)
    keys = _keys(3, seed=7)
    mask = jnp.asarray(np.array([True, False, True]))
    flat = fleet_global_solve(
        st, gr, keys, mask, config=cfg, n_restarts=n_restarts
    )
    moves, objs = decode_fleet_global(
        np.asarray(flat), tenants=3, num_services=graphs[0].num_services
    )
    # the masked slot never emits a move whatever its (filler) state says
    assert moves[1] == []
    for t in (0, 2):
        solo_state, solo_info = solve_with_restarts(
            states[t], graphs[t], keys[t], n_restarts=n_restarts, config=cfg
        )
        assert moves[t] == _solo_changed_moves(states[t], solo_state)
        # objective equality is EXACT: same traced body, same key stream
        assert objs[t][1] == float(solo_info["objective_after"])
        if n_restarts == 1:
            assert objs[t][0] == float(solo_info["objective_before"])
            assert objs[t][2] == bool(solo_info["improved"])
        else:
            # the restart path's absent-keys contract (solo parity)
            assert objs[t][0] is None and objs[t][2] is None


@pytest.mark.parametrize("n_restarts", [1, 2])
def test_fleet_global_dp_plane_matches_vmap_plane(n_restarts):
    """dp shard_map == vmap plane, bit-exact, restart fan-out included —
    on the EXACT-objective configuration (comm + disruption pricing;
    integer-valued at mubench weights). The sqrt-balance term's
    cross-partitioning reduction order can flip near-tie admissions
    between differently-partitioned executables (see parallel/fleet.py),
    so balance runs pin vmap-vs-solo bitwise (the solo cases above) and
    dp-vs-vmap to never-worse quality below."""
    from kubernetes_rescheduling_tpu.parallel.fleet import (
        _fleet_mesh,
        decode_fleet_global_dp,
        fleet_global_solve_dp,
    )

    fleet = _mubench_fleet(2)
    _, graphs, st, gr = _stacked(fleet)
    cfg = GlobalSolverConfig(sweeps=3, balance_weight=0.0, move_cost=0.5)
    keys = _keys(2, seed=3)
    mask = jnp.ones((2,), bool)
    f1 = fleet_global_solve(
        st, gr, keys, mask, config=cfg, n_restarts=n_restarts
    )
    f2 = fleet_global_solve_dp(
        st, gr, keys, mask, config=cfg, n_restarts=n_restarts
    )
    m1, o1 = decode_fleet_global(
        np.asarray(f1), tenants=2, num_services=graphs[0].num_services
    )
    # on the 8-device virtual CPU mesh the auto mesh shards dp=2 — the
    # decode must be told the real dp extent (per-shard block layout)
    dp = _fleet_mesh(2, None).shape["dp"]
    m2, o2 = decode_fleet_global_dp(
        np.asarray(f2), tenants=2, num_services=graphs[0].num_services, dp=dp
    )
    assert dp == 2  # the conftest virtual mesh really sharded tenants
    assert m1 == m2
    assert o1 == o2


@pytest.mark.slow  # the dp-vs-vmap plane parity stays pinned fast by the
# test_fleet_global_dp_plane_matches_vmap_plane cases above (exact-objective
# configuration, bitwise); this balance-weight run only re-checks the
# documented near-tie quality class with its own ~14 s compile
def test_fleet_global_dp_plane_never_worse_under_balance():
    """With the sqrt-balance term on, dp and vmap may legitimately adopt
    different near-tie optima (ulp-order flips across differently
    partitioned executables — parallel/fleet.py documents the boundary)
    — but both must stay in the never-worse family: adopted objectives
    at or below the input's, and the same quality class."""
    from kubernetes_rescheduling_tpu.parallel.fleet import (
        _fleet_mesh,
        decode_fleet_global_dp,
        fleet_global_solve_dp,
    )

    fleet = _mubench_fleet(2)
    _, graphs, st, gr = _stacked(fleet)
    cfg = GlobalSolverConfig(sweeps=3, balance_weight=0.5)
    keys = _keys(2, seed=3)
    mask = jnp.ones((2,), bool)
    f1 = fleet_global_solve(st, gr, keys, mask, config=cfg)
    f2 = fleet_global_solve_dp(st, gr, keys, mask, config=cfg)
    _, o1 = decode_fleet_global(
        np.asarray(f1), tenants=2, num_services=graphs[0].num_services
    )
    dp = _fleet_mesh(2, None).shape["dp"]
    _, o2 = decode_fleet_global_dp(
        np.asarray(f2), tenants=2, num_services=graphs[0].num_services, dp=dp
    )
    for (b1, a1, _i1, _p1), (b2, a2, _i2, _p2) in zip(o1, o2):
        # the solver's contract on BOTH planes: never worse than the
        # input (which near-tie optimum a plane lands on is not part of
        # it — a 3-sweep annealed search on a toy instance has high
        # variance between legitimate optima)
        assert b1 == b2  # same input objective (exact: same snapshot)
        assert a1 <= b1 + 1e-4
        assert a2 <= b2 + 1e-4


def test_fleet_global_steady_state_single_trace(registry):
    fleet = _mubench_fleet(4)
    _, graphs, st, gr = _stacked(fleet)
    cfg = GlobalSolverConfig(sweeps=2)
    mask = jnp.ones((4,), bool)
    for rnd in range(3):
        jax.block_until_ready(
            fleet_global_solve(st, gr, _keys(4, rnd), mask, config=cfg)
        )
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_global_solve").value == 1


# ---------------- batched proactive plane ----------------


def test_fleet_forecast_bit_exact_vs_solo_kernel():
    """The stacked RLS state evolves bit-exactly with the solo JITTED
    forecast kernel per tenant — including rounds where one tenant is
    masked out (a skipped tenant round must not train its model)."""
    fleet = _mubench_fleet(3)
    states, _, _, _ = _stacked(fleet)
    n = states[0].num_nodes
    sc = (
        jnp.float32(1e-3), jnp.float32(0.0), jnp.float32(4),
        jnp.float32(0.85), jnp.float32(0.97),
    )
    # scalars as traced ARGUMENTS — the production solo plane's dispatch
    # shape (closing over them as constants changes XLA's folding enough
    # to drift the RLS statistics at the ulp level)
    solo_jit = jax.jit(forecast_step)
    fstack = init_fleet_forecast_state(2, 3, n)
    fsolo = [init_forecast_state(2, n) for _ in range(3)]
    for rnd in range(9):
        sts = [
            s.replace(
                node_base_cpu=s.node_base_cpu + 7.0 * rnd * ((t + 1) % 2 + 1)
            )
            for t, s in enumerate(states)
        ]
        stk = stack_tenants(sts)
        mask = np.array([True, rnd % 3 != 0, True])
        fstack, dstack, diagstack = _fleet_forecast(
            stk, fstack, jnp.asarray(mask), *sc
        )
        for t in range(3):
            if not mask[t]:
                # inert slot: no delta, no diag, untouched state
                assert not np.asarray(dstack[t]).any()
                assert not np.asarray(diagstack[t]).any()
                continue
            fsolo[t], d, diag = solo_jit(sts[t], fsolo[t], *sc)
            assert np.array_equal(np.asarray(dstack[t]), np.asarray(d))
            assert np.array_equal(np.asarray(diagstack[t]), np.asarray(diag))
            for name in ("A", "b", "history", "err_model_sum"):
                assert np.array_equal(
                    np.asarray(getattr(fsolo[t], name)),
                    np.asarray(getattr(fstack, name)[t]),
                ), name


def test_fleet_forecast_repad_grows_cold_slots():
    fst = init_fleet_forecast_state(2, 3, 4)
    grown = repad_fleet_forecast_state(fst, 8)
    assert grown.history.shape == (3, 3, 8)
    assert grown.A.shape == (3, 8, 3, 3)
    with pytest.raises(ValueError, match="shrink"):
        repad_fleet_forecast_state(grown, 4)


def test_fleet_proactive_decide_bit_exact_vs_solo():
    """The batched predicted-state decide equals the solo proactive
    kernel per tenant under shared deltas — vmap AND dp planes."""
    from kubernetes_rescheduling_tpu.parallel.fleet import (
        fleet_solve_proactive_dp,
    )
    from kubernetes_rescheduling_tpu.solver.fleet import (
        ROW_MOST,
        ROW_SERVICE,
        ROW_TARGET,
        ROW_VICTIM,
    )

    fleet = _mubench_fleet(3)
    states, graphs, st, gr = _stacked(fleet)
    pid = jnp.asarray(POLICY_IDS["communication"])
    thr = jnp.asarray(30.0)
    keys = _keys(3, seed=2)
    mask = jnp.asarray(np.array([True, True, False]))
    n = states[0].num_nodes
    # a nonzero per-tenant delta pattern so the predicted state differs
    deltas = jnp.stack(
        [jnp.full((n,), 120.0 * (t + 1), jnp.float32) for t in range(3)]
    )
    d1, h1 = fleet_solve_proactive(st, gr, pid, thr, keys, mask, deltas)
    d2, h2 = fleet_solve_proactive_dp(st, gr, pid, thr, keys, mask, deltas)
    assert np.array_equal(np.asarray(d1), np.asarray(d2))
    assert np.array_equal(np.asarray(h1), np.asarray(h2))
    decisions = np.asarray(d1)
    for t in range(2):
        most, hz, victim, svc, target = jax.jit(decide_with_forecast)(
            states[t], graphs[t], pid, thr, keys[t], deltas[t]
        )
        assert decisions[t, ROW_MOST] == int(most)
        assert decisions[t, ROW_VICTIM] == int(victim)
        assert decisions[t, ROW_SERVICE] == int(svc)
        assert decisions[t, ROW_TARGET] == int(target)
        assert np.array_equal(np.asarray(h1)[t], np.asarray(hz))
    # the masked slot is a no-op row
    assert decisions[2, ROW_MOST] == -1
    assert not np.asarray(h1)[2].any()


# ---------------- multiplexed controller, new planes ----------------


def _solo_vs_fleet(algo, rounds=4, tenants=3, seed=1, key_seed=3, **extra):
    key = jax.random.PRNGKey(key_seed)
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=tenants),
        **extra,
    )
    res = run_fleet_controller(_mubench_fleet(tenants, seed=seed), cfg, key=key)
    solo_cfg = RescheduleConfig(
        algorithm=algo, max_rounds=rounds, sleep_after_action_s=0.0, **extra
    )
    solo_fleet = _mubench_fleet(tenants, seed=seed)
    out = []
    for t, (name, backend) in enumerate(solo_fleet):
        solo = run_controller(backend, solo_cfg, key=jax.random.fold_in(key, t))
        out.append((name, solo, res.results[name]))
    return out


def test_fleet_global_controller_matches_n_solo_controllers():
    """The multiplexed GLOBAL loop IS N solo global loops on one device
    plane: same applied moves in the same order, same solver
    objectives, same post-round metrics."""
    for name, solo, fl in _solo_vs_fleet("global", balance_weight=0.5):
        assert len(solo.rounds) == len(fl.rounds) == 4
        for a, b in zip(solo.rounds, fl.rounds):
            assert a.services_moved == b.services_moved
            assert a.moved == b.moved
            assert [m for m in a.applied_moves] == [m for m in b.applied_moves]
            assert a.objective_after == pytest.approx(
                b.objective_after, rel=1e-6
            )
            assert a.solver_improved == b.solver_improved
            assert a.communication_cost == pytest.approx(
                b.communication_cost, rel=1e-5
            )
            assert a.load_std == pytest.approx(b.load_std, rel=1e-5)


def test_fleet_proactive_controller_matches_n_solo_controllers():
    """The multiplexed PROACTIVE loop: per-tenant forecast state,
    skill-gated deltas, and decisions all match N solo proactive runs
    (cold rounds are reactive-identical by the zero-delta contract)."""
    fc = ForecastConfig(min_history=4)
    for name, solo, fl in _solo_vs_fleet(
        "proactive", rounds=6, forecast=fc
    ):
        assert len(solo.rounds) == len(fl.rounds) == 6
        for a, b in zip(solo.rounds, fl.rounds):
            assert (a.most_hazard, a.service, a.target, a.moved) == (
                b.most_hazard, b.service, b.target, b.moved,
            )
            assert a.communication_cost == pytest.approx(
                b.communication_cost, rel=1e-5
            )
            fa, fb = a.forecast, b.forecast
            assert (fa is None) == (fb is None)
            if fa is not None:
                assert fa["mode"] == fb["mode"]
                assert fa["skill"] == pytest.approx(fb["skill"], abs=1e-6)
                assert fa["trained"] == fb["trained"]


def test_fleet_heterogeneous_tenants_match_unpadded_solo(registry):
    """Heterogeneous shapes: a fleet of two different-sized tenants is
    aligned to ONE shared shape bucket (padded, mask-threaded), and the
    smaller tenant's decisions are bit-exact with its UNPADDED solo run
    — the mask-twin contract at the loop level."""
    def small():
        b = make_backend("mubench", 1)
        b.inject_imbalance(b.node_names[0])
        return b

    big = make_backend("mubench", 2)
    extra = dataclasses.replace(
        big.workmodel.services[0], name="extra-svc", replicas=2
    )
    big.deploy_service(extra)
    big.inject_imbalance(big.node_names[0])
    fleet = FleetBackend(backends=[small(), big])
    key = jax.random.PRNGKey(5)
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=3,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=2),
    )
    res = run_fleet_controller(fleet, cfg, key=key, registry=registry)
    # the shared bucket was actually fitted (and is a power of two)
    svc_cap = registry.gauge("fleet_bucket_services").value
    assert svc_cap >= 21 and (int(svc_cap) & (int(svc_cap) - 1)) == 0
    solo = run_controller(
        small(),
        RescheduleConfig(
            algorithm="communication", max_rounds=3, sleep_after_action_s=0.0
        ),
        key=jax.random.fold_in(key, 0),
    )
    frounds = res.results["tenant0"].rounds
    assert len(solo.rounds) == len(frounds) == 3
    for a, b in zip(solo.rounds, frounds):
        assert (a.most_hazard, a.service, a.target, a.moved) == (
            b.most_hazard, b.service, b.target, b.moved,
        )
        assert a.communication_cost == pytest.approx(
            b.communication_cost, rel=1e-5
        )


@pytest.mark.parametrize("algo,extra", [
    ("global", {"balance_weight": 0.5}),
    ("proactive", {}),
])
def test_fleet_new_planes_chaos_isolation(registry, algo, extra):
    """The isolation acceptance pin on the NEW planes: a seeded chaos
    soak on the last tenant leaves every other tenant's executed-round
    counts and cost trajectories identical to a no-chaos run."""
    key = jax.random.PRNGKey(0)

    def run(chaos: bool):
        fleet = _mubench_fleet(3)
        cfg = RescheduleConfig(
            algorithm=algo,
            max_rounds=8,
            sleep_after_action_s=0.0,
            retry=RetryPolicy(max_attempts=1, base_delay_s=0.01),
            max_consecutive_failures=2,
            breaker_cooldown_rounds=2,
            chaos=ChaosConfig(profile="soak" if chaos else "none", seed=5),
            fleet=FleetConfig(
                tenants=3, chaos_tenants=(2,) if chaos else ()
            ),
            **extra,
        )
        return run_fleet_controller(fleet, cfg, key=key, registry=registry)

    clean = run(False)
    chaotic = run(True)
    for name in ("tenant0", "tenant1"):
        a, b = clean.results[name], chaotic.results[name]
        assert len(a.rounds) == len(b.rounds) == 8
        assert a.skipped_rounds == b.skipped_rounds == 0
        assert [r.communication_cost for r in a.rounds] == [
            r.communication_cost for r in b.rounds
        ]
        assert [r.services_moved for r in a.rounds] == [
            r.services_moved for r in b.rounds
        ]
    t2 = chaotic.results["tenant2"]
    assert len(t2.rounds) + t2.skipped_rounds == 8
    assert t2.boundary_failures > 0


def test_fleet_one_transfer_per_round_on_new_planes(registry):
    """The fleet transfer discipline survives the new planes: per
    executed round exactly ONE fleet_decision pull (decisions + hazard
    [+ forecast diag] or the global move bundle) and ONE fleet_metrics
    pull — statically enforced by check_apply_boundary, counted here."""
    for algo, extra in (
        ("global", {"balance_weight": 0.5}),
        ("proactive", {}),
    ):
        reg = MetricsRegistry()
        prev = set_registry(reg)
        try:
            cfg = RescheduleConfig(
                algorithm=algo,
                max_rounds=3,
                sleep_after_action_s=0.0,
                fleet=FleetConfig(tenants=2),
                **extra,
            )
            run_fleet_controller(
                _mubench_fleet(2), cfg, key=jax.random.PRNGKey(0),
                registry=reg,
            )
            transfers = reg.counter(
                "device_transfers_total", labelnames=("site",)
            )
            assert transfers.labels(site="fleet_decision").value == 3, algo
            assert transfers.labels(site="fleet_metrics").value == 3, algo
        finally:
            set_registry(prev)


# ---------------- T >= 256 scale pin ----------------


def test_fleet_bundle_is_one_transfer_at_t256(registry):
    """The acceptance-scale pin: at T=256 tenants the whole fleet
    round's decisions still come home as ONE flat bundle = ONE counted
    pull, from ONE steady-state trace (tiny per-tenant clusters — the
    tenant-axis mechanics are what is under test; bench-scale cells are
    the slow-marked matrix variant below)."""
    from kubernetes_rescheduling_tpu.telemetry import pull

    T = 256
    b = make_backend("mubench", 1)
    state, graph = b.monitor(), b.comm_graph()
    st = jax.tree_util.tree_map(
        lambda x: jnp.tile(x[None], (T,) + (1,) * x.ndim), state
    )
    gr = jax.tree_util.tree_map(
        lambda x: jnp.tile(x[None], (T,) + (1,) * x.ndim), graph
    )
    keys = _keys(T)
    mask = jnp.ones((T,), bool)
    cfg = GlobalSolverConfig(sweeps=2)
    for rnd in range(2):
        flat = fleet_global_solve(
            st, gr, _keys(T, rnd), mask, config=cfg
        )
    got = pull(flat, site="fleet_decision", registry=registry)
    moves, objs = decode_fleet_global(
        got, tenants=T, num_services=graph.num_services
    )
    assert len(moves) == T
    # every tenant slot decoded from the ONE transfer
    transfers = registry.counter(
        "device_transfers_total", labelnames=("site",)
    )
    assert transfers.labels(site="fleet_decision").value == 1
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="fleet_global_solve").value == 1


# ---------------- solver-cache eviction ----------------


def test_solver_cache_evicts_on_churn(registry):
    """Counted eviction: churn that rewrites a tenant's graph drops that
    tenant's solver-cache slots from the raw backend instead of leaving
    stale derived graphs resident for the life of the soak."""
    fleet = _mubench_fleet(2)
    # pre-populate tenant0's slot the way a solo sparse/pod run would
    ba = BoundaryClient(fleet.backends[0], tenant="tenant0", registry=None)
    ba.registry = registry
    slot = ba.solver_cache("sparse_graph")
    slot["graph"], slot["value"] = "g-old", "v-old"
    assert ba.evict_solver_caches(reason="churn") == 1
    assert ba.solver_cache("sparse_graph") == {}
    evs = registry.counter(
        "solver_cache_evictions_total", labelnames=("reason",)
    )
    assert evs.labels(reason="churn").value == 1
    # idempotent: nothing left to evict
    ba.solver_cache("sparse_graph").clear()
    getattr(ba.raw_backend, "_solver_caches").clear()
    assert ba.evict_solver_caches(reason="churn") == 0


def test_fleet_loop_evicts_caches_under_deploy_waves(registry):
    """Loop-level: a deploy-waves fleet soak counts cache evictions the
    round churn rewrites a tenant's graph (the slots were populated
    before the run, as a prior solo run would leave them)."""
    fleet = _mubench_fleet(2)
    for t, b in enumerate(fleet.backends):
        bc = BoundaryClient(b, tenant=f"tenant{t}")
        bc.solver_cache("sparse_graph")["value"] = f"stale-{t}"
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=6,
        sleep_after_action_s=0.0,
        fleet=FleetConfig(tenants=2),
        elastic=ElasticConfig(profile="deploy-waves", seed=3),
    )
    run_fleet_controller(
        fleet, cfg, key=jax.random.PRNGKey(0), registry=registry
    )
    evs = registry.counter(
        "solver_cache_evictions_total", labelnames=("reason",)
    )
    total = sum(
        evs.labels(reason=r).value for r in ("churn", "promotion")
    )
    assert total >= 1
    caches = getattr(fleet.backends[0], "_solver_caches", {})
    assert all("stale" not in str(v) for v in caches.values())


# ---------------- slow fleet-matrix cells ----------------


@pytest.mark.slow  # the 1k-tenant fleet-matrix cell at bench-like tenant
# count; the tenant-axis mechanics stay pinned fast by
# test_fleet_bundle_is_one_transfer_at_t256 and the parity cases above
def test_fleet_matrix_1k_tenants_single_dispatch():
    """1024 tenants advanced by ONE batched greedy dispatch + ONE pull,
    from one steady-state trace — the MULTICHIP_r06 fleet-matrix shape
    (tiny per-tenant clusters on CPU; the 2k×256 per-tenant cells run
    on-rig via BENCH_SCENARIO=fleet BENCH_TENANTS=1024)."""
    from kubernetes_rescheduling_tpu.solver.fleet import fleet_solve
    from kubernetes_rescheduling_tpu.telemetry import pull

    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        T = 1024
        b = make_backend("mubench", 1)
        state, graph = b.monitor(), b.comm_graph()
        st = jax.tree_util.tree_map(
            lambda x: jnp.tile(x[None], (T,) + (1,) * x.ndim), state
        )
        gr = jax.tree_util.tree_map(
            lambda x: jnp.tile(x[None], (T,) + (1,) * x.ndim), graph
        )
        pid = jnp.asarray(POLICY_IDS["communication"])
        mask = jnp.ones((T,), bool)
        for rnd in range(2):
            decisions_dev, hazard_dev = fleet_solve(
                st, gr, pid, jnp.asarray(30.0), _keys(T, rnd), mask
            )
        flat = pull(
            jnp.concatenate(
                [
                    jnp.ravel(decisions_dev).astype(jnp.float32),
                    jnp.ravel(hazard_dev).astype(jnp.float32),
                ]
            ),
            site="fleet_decision",
            registry=reg,
        )
        assert flat.shape[0] == T * 4 + T * state.num_nodes
        traces = reg.counter("jax_traces_total", labelnames=("fn",))
        assert traces.labels(fn="fleet_solve").value == 1
        transfers = reg.counter(
            "device_transfers_total", labelnames=("site",)
        )
        assert transfers.labels(site="fleet_decision").value == 1
    finally:
        set_registry(prev)
