"""Forecast plane: the online forecaster, the ``proactive`` algorithm,
and their audit invariants.

Pinned invariants:

- **oracle twin** — the batched JAX ridge fit and its predictions match
  the independent numpy re-derivation (``oracle/forecast.py``) within
  f32 tolerance, and the ONLINE kernel's accumulated fit reproduces the
  offline fit over the same windows (the ``oracle/optimum`` precedent);
- **reactive equivalence** — a cold (or skill-degraded) forecaster
  yields proactive rounds bit-identical to plain reactive CAR: same
  moves, same targets, same costs, never a NaN;
- **mask twins** — the forecast kernel and the predicted-state decision
  kernels on a padded + masked problem reproduce the unpadded twin
  (padded slots carry exactly zero delta);
- **acceptance head-to-head** — on seeded churned soaks, ``proactive``
  achieves mean communication cost ≤ reactive CAR's with
  ``forecast_skill > 0`` vs the persistence baseline, the proactive
  kernels compile exactly ``1 + bucket promotions`` times, and every
  proactive round's explanation re-derives its decision.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.harness import (
    make_backend,
    run_forecast_headtohead,
)
from kubernetes_rescheduling_tpu.config import (
    ElasticConfig,
    FleetConfig,
    ForecastConfig,
    ObsConfig,
    RescheduleConfig,
)
from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.forecast.dataset import (
    build_dataset,
    edge_traffic_series,
    load_rounds,
    node_load_series,
    report_dataset,
)
from kubernetes_rescheduling_tpu.forecast.model import (
    DIAG_SKILL,
    DIAG_TRAINED,
    ForecastState,
    fit_ridge,
    forecast_step,
    init_forecast_state,
    node_loads,
    repad_forecast_state,
    ridge_predict,
)
from kubernetes_rescheduling_tpu.oracle.forecast import (
    difference_windows,
    eval_forecast_np,
    fit_ridge_np,
    predict_np,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS
from kubernetes_rescheduling_tpu.policies.proactive import (
    predicted_state,
    scoring_policy,
)
from kubernetes_rescheduling_tpu.solver.round_loop import (
    decide,
    decide_with_forecast,
)
from kubernetes_rescheduling_tpu.telemetry import MetricsRegistry, set_registry
from kubernetes_rescheduling_tpu.telemetry.explain import (
    explanation_consistent,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import (
    RULE_FORECAST,
    SLORules,
    Watchdog,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@pytest.fixture()
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _loads_state(loads, valid=None) -> ClusterState:
    """A minimal state whose node_cpu_used() IS ``loads`` (cap 1.0, so
    load fractions equal millicores — convenient for kernel math)."""
    loads = np.asarray(loads, np.float32)
    n = loads.shape[0]
    z = jnp.zeros
    return ClusterState(
        node_cpu_cap=jnp.ones((n,), jnp.float32),
        node_mem_cap=jnp.ones((n,), jnp.float32),
        node_base_cpu=jnp.asarray(loads),
        node_base_mem=z((n,), jnp.float32),
        node_valid=(
            jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
        ),
        node_lex_rank=jnp.arange(n, dtype=jnp.int32),
        pod_node=z((0,), jnp.int32),
        pod_service=z((0,), jnp.int32),
        pod_cpu=z((0,), jnp.float32),
        pod_mem=z((0,), jnp.float32),
        pod_valid=z((0,), bool),
    )


def _scalars(cfg: ForecastConfig):
    return (
        jnp.float32(cfg.ridge),
        jnp.float32(cfg.min_skill),
        jnp.float32(cfg.min_history),
        jnp.float32(cfg.decay),
        jnp.float32(cfg.fit_decay),
    )


def _replay(series, cfg: ForecastConfig, valid=None):
    """Drive the online kernel over a [T, N] series; returns the final
    state plus per-round (delta, diag)."""
    t, n = np.asarray(series).shape
    fst = init_forecast_state(cfg.lags, n)
    step = jax.jit(forecast_step)
    outs = []
    for i in range(t):
        v = None if valid is None else valid[i]
        fst, delta, diag = step(
            _loads_state(series[i], valid=v), fst, *_scalars(cfg)
        )
        outs.append((np.asarray(delta), np.asarray(diag)))
    return fst, outs


# ------------------------------------------------------- oracle twins


def test_fit_ridge_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    series = np.cumsum(rng.normal(0, 0.1, (30, 5)), axis=0)
    mask = rng.random((30, 5)) > 0.15
    X, y, base, w = difference_windows(series, mask, lags=3)
    W_jax = np.asarray(fit_ridge(X, y, w, 1e-3))
    W_np = fit_ridge_np(X, y, w, 1e-3)
    np.testing.assert_allclose(W_jax, W_np, rtol=2e-3, atol=2e-4)
    pred_jax = np.asarray(ridge_predict(jnp.asarray(W_jax), jnp.asarray(X)))
    pred_np = predict_np(W_np, X)
    np.testing.assert_allclose(pred_jax, pred_np, rtol=2e-3, atol=2e-4)


def test_online_kernel_matches_offline_fit_on_clean_series():
    """With no forgetting, the online sufficient statistics accumulate
    exactly the offline windows: the kernel's next-step prediction must
    equal the oracle's ridge fit applied to the same final features."""
    rng = np.random.default_rng(1)
    t_steps, n = 18, 4
    series = 0.4 + 0.1 * np.sin(np.arange(t_steps))[:, None] + np.cumsum(
        rng.normal(0, 0.01, (t_steps, n)), axis=0
    )
    series = np.clip(series, 0.01, None).astype(np.float32)
    cfg = ForecastConfig(lags=2, min_history=5, decay=1.0, fit_decay=1.0)
    fst, outs = _replay(series, cfg)
    # offline: same difference windows, same ridge
    X, y, base, w = difference_windows(series, None, lags=cfg.lags)
    W = fit_ridge_np(X, y, w, cfg.ridge)
    diffs = np.diff(series, axis=0)
    x_next = np.concatenate(
        [diffs[-cfg.lags:], np.ones((1, n))], axis=0
    ).T  # [N, F]
    offline_pred = np.maximum(
        series[-1] + np.einsum("nf,nf->n", W, x_next), 0.0
    )
    online_pred = np.asarray(fst.prev_model_pred)
    np.testing.assert_allclose(online_pred, offline_pred, rtol=2e-3, atol=2e-4)


def test_eval_forecast_np_beats_persistence_on_trending_series():
    """Sanity anchor for the skill metric itself: on a noisy trending
    series the difference-ridge model must report positive skill."""
    rng = np.random.default_rng(2)
    t = np.arange(120)
    series = (
        0.5
        + 0.3 * np.sin(t / 12.0)[:, None]
        + rng.normal(0, 0.01, (120, 6))
    )
    out = eval_forecast_np(series, None, lags=2, ridge=1e-3)
    assert out["windows"] > 0
    assert out["skill"] > 0.1
    assert out["mae_model"] < out["mae_persistence"]


# ------------------------------------------- reactive equivalence


def _static_run(algo, *, seed=3, rounds=6, forecast=None, noise=0.0):
    backend = make_backend("mubench", seed=seed)
    if noise:
        backend.load = dataclasses.replace(backend.load, noise_frac=noise)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm=algo,
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=seed,
        forecast=forecast if forecast is not None else ForecastConfig(),
    )
    return run_controller(backend, cfg, key=jax.random.PRNGKey(seed))


def test_cold_start_bit_identical_to_reactive_car():
    """Satellite: with insufficient history the forecaster predicts
    persistence (delta exactly 0.0), so every proactive round is
    bit-identical to plain CAR — and nothing is ever NaN."""
    fc = ForecastConfig(min_history=100)  # never trains in 6 rounds
    pro = _static_run("proactive", forecast=fc)
    rea = _static_run("communication")
    assert len(pro.rounds) == len(rea.rounds)
    for p, r in zip(pro.rounds, rea.rounds):
        assert p.services_moved == r.services_moved
        assert p.target == r.target
        assert p.most_hazard == r.most_hazard
        assert p.communication_cost == r.communication_cost  # bit-equal f32
        assert p.load_std == r.load_std
        assert p.forecast is not None and p.forecast["mode"] == "cold"
        for v in (p.forecast["skill"], p.forecast["mae_model"],
                  p.forecast["mae_persistence"]):
            assert np.isfinite(v)


def test_skill_gate_degrades_to_reactive_decisions():
    """Satellite: an impossible skill floor forces the device-side gate
    to zero the applied delta — trained rounds run as reactive CAR while
    the shadow model keeps being scored."""
    fc = ForecastConfig(min_history=4, min_skill=1.0)
    pro = _static_run("proactive", forecast=fc, noise=0.03, rounds=8)
    rea = _static_run("communication", noise=0.03, rounds=8)
    assert [p.services_moved for p in pro.rounds] == [
        r.services_moved for r in rea.rounds
    ]
    modes = {p.forecast["mode"] for p in pro.rounds}
    assert "predictive" not in modes
    assert "degraded" in modes  # trained, but gated off
    # the shadow model kept scoring: skill is being measured, not frozen
    assert any(p.forecast["skill"] != 0.0 for p in pro.rounds)


# ------------------------------------------------------- mask twins


def test_mask_twin_forecast_and_proactive_decide():
    """Satellite: the forecast kernel on a padded + masked problem
    reproduces the unpadded twin — real slots match, padded slots carry
    exactly zero delta — and the predicted-state decision kernel emits
    bit-identical decisions."""
    rng = np.random.default_rng(4)
    t_steps, n = 8, 3
    series = np.clip(
        0.4 + np.cumsum(rng.normal(0, 0.05, (t_steps, n)), axis=0), 0.01, None
    ).astype(np.float32)
    cfg = ForecastConfig(lags=2, min_history=5)
    _, outs = _replay(series, cfg)
    padded = np.zeros((t_steps, 8), np.float32)
    padded[:, :n] = series
    pvalid = np.zeros((t_steps, 8), bool)
    pvalid[:, :n] = True
    _, pouts = _replay(padded, cfg, valid=pvalid)
    for (d, g), (pd, pg) in zip(outs, pouts):
        np.testing.assert_allclose(pd[:n], d, rtol=1e-5, atol=1e-6)
        assert not pd[n:].any()  # padded slots: exactly zero delta
        np.testing.assert_allclose(pg[DIAG_SKILL], g[DIAG_SKILL],
                                   rtol=1e-5, atol=1e-6)

    # the decision twin: same mubench padded/unpadded pair as the
    # elastic mask twins, decided against the predicted state
    exact = make_backend("mubench", seed=1)
    exact.inject_imbalance(exact.node_names[0])
    pad_b = make_backend("mubench", seed=1)
    pad_b.set_capacities(node=8, pod=64, service=32)
    pad_b.inject_imbalance(pad_b.node_names[0])
    st, gr = exact.monitor(), exact.comm_graph()
    pst, pgr = pad_b.monitor(), pad_b.comm_graph()
    delta = jnp.asarray(np.array([120.0, -40.0, 0.0], np.float32))
    pdelta = jnp.zeros((pst.num_nodes,), jnp.float32).at[:3].set(delta)
    key = jax.random.PRNGKey(9)
    pid = jnp.asarray(POLICY_IDS["communication"])
    thr = jnp.asarray(30.0)
    a = decide_with_forecast(st, gr, pid, thr, key, delta)
    b = decide_with_forecast(pst, pgr, pid, thr, key, pdelta)
    for ai, bi in zip(a[:1] + a[2:], b[:1] + b[2:]):
        assert int(ai) == int(bi)


def test_zero_delta_predicted_state_is_identity():
    backend = make_backend("mubench", seed=2)
    st = backend.monitor()
    ps = predicted_state(st, jnp.zeros((st.num_nodes,), jnp.float32))
    np.testing.assert_array_equal(
        np.asarray(ps.node_base_cpu), np.asarray(st.node_base_cpu)
    )
    key = jax.random.PRNGKey(0)
    pid = jnp.asarray(POLICY_IDS["communication"])
    a = decide(st, backend.comm_graph(), pid, jnp.asarray(30.0), key)
    b = decide_with_forecast(
        st, backend.comm_graph(), pid, jnp.asarray(30.0), key,
        jnp.zeros((st.num_nodes,), jnp.float32),
    )
    for ai, bi in zip(a[:1] + a[2:], b[:1] + b[2:]):
        assert int(ai) == int(bi)


# ------------------------------------------------- state mechanics


def test_repad_grows_and_refuses_shrink():
    fst = init_forecast_state(2, 4)
    grown = repad_forecast_state(fst, 8)
    assert grown.num_nodes == 8 and grown.lags == 2
    assert repad_forecast_state(fst, 4) is fst
    with pytest.raises(ValueError):
        repad_forecast_state(grown, 4)
    with pytest.raises(ValueError):
        init_forecast_state(0, 4)


def test_revalidated_slot_restarts_its_series():
    """A slot that churns away and comes back must not inherit the old
    tenant's history: its first predictions are persistence again."""
    rng = np.random.default_rng(5)
    t_steps, n = 16, 3
    series = np.clip(
        0.5 + np.cumsum(rng.normal(0, 0.05, (t_steps, n)), axis=0), 0.01, None
    ).astype(np.float32)
    valid = np.ones((t_steps, n), bool)
    valid[8:11, 2] = False  # node 2 drains for three rounds
    cfg = ForecastConfig(lags=2, min_history=5)
    fst, outs = _replay(series, cfg, valid=valid)
    # during invalidity: zero delta on the dead slot
    for i in range(8, 11):
        assert outs[i][0][2] == 0.0
    # right after revalidation the slot is cold again (count restarted):
    # persistence prediction = zero delta while others may predict
    assert outs[11][0][2] == 0.0
    assert outs[12][0][2] == 0.0
    assert float(fst.count[2]) == t_steps - 11


def test_never_nan_on_pathological_series():
    series = np.zeros((20, 4), np.float32)
    series[:, 1] = 1e6
    series[::2, 2] = 5.0  # violent alternation
    cfg = ForecastConfig(lags=2, min_history=4)
    _, outs = _replay(series, cfg)
    for d, g in outs:
        assert np.isfinite(d).all()
        assert np.isfinite(g).all()


# ------------------------------------------- config & CLI surface


def test_forecast_config_validation():
    ForecastConfig().validate()
    with pytest.raises(ValueError):
        ForecastConfig(lags=0).validate()
    with pytest.raises(ValueError):
        ForecastConfig(ridge=0.0).validate()
    with pytest.raises(ValueError):
        ForecastConfig(lags=3, min_history=4).validate()
    with pytest.raises(ValueError):
        ForecastConfig(decay=0.0).validate()
    with pytest.raises(ValueError):
        ForecastConfig(fit_decay=1.5).validate()
    with pytest.raises(ValueError):
        ForecastConfig(base_policy="global").validate()
    with pytest.raises(ValueError):
        ObsConfig(slo_forecast_min_skill=1.5).validate()
    # proactive constraints
    RescheduleConfig(algorithm="proactive").validate()
    with pytest.raises(ValueError):
        RescheduleConfig(algorithm="proactive", moves_per_round="all").validate()
    with pytest.raises(ValueError):
        RescheduleConfig(
            algorithm="proactive", placement_unit="pod"
        ).validate()
    # fleet v2: proactive IS fleet-servable now (the batched forecast
    # plane in forecast/fleet.py carries per-tenant RLS state)
    RescheduleConfig(
        algorithm="proactive", fleet=FleetConfig(tenants=2)
    ).validate()
    assert scoring_policy("proactive", ForecastConfig()) == "communication"
    assert scoring_policy("spread", ForecastConfig()) == "spread"


def test_forecast_config_from_toml(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        'algorithm = "proactive"\n'
        "[forecast]\n"
        "lags = 4\n"
        "ridge = 0.01\n"
        "min_history = 9\n"
        "min_skill = -0.1\n"
        "decay = 0.8\n"
        "fit_decay = 0.95\n"
        'base_policy = "spread"\n'
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.forecast == ForecastConfig(
        lags=4, ridge=0.01, min_history=9, min_skill=-0.1, decay=0.8,
        fit_decay=0.95, base_policy="spread",
    )


def test_cli_proactive_smoke(capsys):
    from kubernetes_rescheduling_tpu.cli import main

    rc = main([
        "reschedule", "--algorithm", "proactive", "--scenario", "mubench",
        "--rounds", "2", "--imbalance", "--forecast-lags", "2",
        "--forecast-min-history", "4",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["algorithm"] == "proactive"
    assert len(out["rounds"]) == 2
    assert out["rounds"][0]["forecast"]["mode"] == "cold"


# --------------------------------------------------- watchdog rule


class _Rec:
    def __init__(self, **kw):
        self.decision_latency_s = 0.01
        self.communication_cost = 10.0
        self.__dict__.update(kw)


def test_watchdog_forecast_skill_rule(registry):
    wd = Watchdog(SLORules(max_retraces=0), registry=registry)
    # reactive rounds (no forecast block): rule never fires
    assert wd.observe_round(_Rec()) == []
    # an untrained forecaster is warming up, not violating
    wd.observe_round(_Rec(forecast={"trained": False, "skill": -1.0}))
    assert RULE_FORECAST not in wd.active
    # trained and losing to persistence: violation
    raised = wd.observe_round(
        _Rec(forecast={"trained": True, "skill": -0.4, "mode": "degraded",
                       "mae_model": 0.2, "mae_persistence": 0.1})
    )
    assert [r["rule"] for r in raised] == [RULE_FORECAST]
    assert not wd.healthy
    # recovery clears it
    wd.observe_round(
        _Rec(forecast={"trained": True, "skill": 0.2, "mode": "predictive"})
    )
    assert RULE_FORECAST not in wd.active and wd.healthy
    # rebase forgets the forecast context entirely
    wd.observe_round(
        _Rec(forecast={"trained": True, "skill": -0.4, "mode": "degraded"})
    )
    assert RULE_FORECAST in wd.active
    wd.rebase()
    assert wd.check() == [] and RULE_FORECAST not in wd.active
    snap = {
        (r["metric"], tuple(sorted(r["labels"].items()))): r.get("value")
        for r in registry.snapshot()
    }
    assert snap[("slo_violations_total", (("rule", RULE_FORECAST),))] == 2


# ------------------------------------------------- metric families


def test_forecast_metrics_and_rounds_jsonl(registry):
    fc = ForecastConfig(lags=2, min_history=4)
    res = _static_run("proactive", forecast=fc, rounds=6, noise=0.02)
    assert all(r.forecast is not None for r in res.rounds)
    d = res.rounds[-1].as_dict()
    assert "forecast" in d and json.loads(json.dumps(d["forecast"]))
    snap = {
        (r["metric"], tuple(sorted(r["labels"].items()))): r.get("value")
        for r in registry.snapshot()
    }
    assert ("forecast_skill", (("target", "node_load"),)) in snap
    assert ("forecast_mae", (("target", "node_load"),)) in snap
    total = sum(
        v for (m, _l), v in snap.items() if m == "forecast_rounds_total"
    )
    assert total == len(res.rounds)


# --------------------------------------------------------- dataset


def _fake_rounds(t=14, nodes=("n0", "n1"), edges=(("a", "b"), ("b", "c"))):
    rng = np.random.default_rng(6)
    rounds = []
    for i in range(t):
        ingress = {n: 1.0 + 0.1 * i + rng.normal(0, 0.01) for n in nodes}
        egress = {n: 0.5 + 0.05 * i for n in nodes}
        rounds.append({
            "round": i + 1,
            "communication_cost": 10.0,
            "attribution": {
                "total": 10.0,
                "tail": 0.0,
                "edges": [
                    {"src_service": s, "dst_service": d,
                     "cost": 2.0 + 0.2 * i + j}
                    for j, (s, d) in enumerate(edges)
                ],
                "ingress": ingress,
                "egress": egress,
            },
        })
    return rounds


def test_dataset_extraction_and_windows(tmp_path):
    rounds = _fake_rounds()
    names, series, mask = node_load_series(rounds)
    assert names == ["n0", "n1"]
    assert series.shape == (14, 2) and mask.all()
    # ingress + egress per node
    assert series[0, 0] == pytest.approx(
        rounds[0]["attribution"]["ingress"]["n0"]
        + rounds[0]["attribution"]["egress"]["n0"]
    )
    keys, eseries, emask = edge_traffic_series(rounds)
    assert keys == ["a->b", "b->c"] and eseries.shape == (14, 2)
    ds = build_dataset(rounds, lags=3)
    assert ds["node_load"]["X"].shape == (2, 10, 4)
    assert ds["edge_traffic"]["y_delta"].shape == (2, 10)
    # missing attribution rows are MASKED, not zero-filled
    partial = list(rounds)
    partial[5] = {"round": 6}  # no attribution: round dropped entirely
    _, s2, m2 = node_load_series(partial)
    assert s2.shape[0] == 13
    # an edge absent from one round's top-k is masked for that round
    censored = [json.loads(json.dumps(r)) for r in rounds]
    censored[4]["attribution"]["edges"] = censored[4]["attribution"]["edges"][:1]
    _, _es, em = edge_traffic_series(censored)
    assert em[4, 0] and not em[4, 1]


def test_dataset_report_and_cli(tmp_path, capsys):
    p = tmp_path / "rounds.jsonl"
    p.write_text(
        "\n".join(json.dumps(r, default=float) for r in _fake_rounds(t=20))
    )
    text = report_dataset([p], lags=2)
    assert "node_load" in text and "edge_traffic" in text
    assert "skill" in text
    # the trending fake series is learnable: the oracle fit beats
    # persistence on at least the node family
    assert "beats persistence" in text
    assert load_rounds([p])[0]["round"] == 1

    from kubernetes_rescheduling_tpu.cli import main

    rc = main(["telemetry", "dataset", str(p), "--dataset-lags", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "forecast dataset" in out and "node_load" in out


# ------------------------------------------------------ acceptance


def test_acceptance_proactive_vs_reactive_diurnal(registry):
    """THE acceptance head-to-head (ISSUE 8): seeded diurnal-autoscale
    soak, proactive vs reactive CAR on identical clusters. Proactive's
    mean communication cost must not exceed reactive's, the trained
    forecaster must beat the persistence baseline (skill > 0), both
    proactive kernels must compile exactly 1 + counted bucket
    promotions times, and every proactive round must remain
    explanation-consistent."""
    out = run_forecast_headtohead(
        profiles=("diurnal-autoscale",),
        logger_factory=lambda: StructuredLogger(name="forecast-h2h"),
        registry=registry,
    )
    cell = out["profiles"]["diurnal-autoscale"]
    pro, rea = cell["proactive"], cell["communication"]
    assert pro["rounds"] > 0 and rea["rounds"] > 0
    # the headline claim: predicting the next window never costs comm
    assert (
        pro["mean_communication_cost"]
        <= rea["mean_communication_cost"] * (1 + 1e-6)
    )
    # the forecaster earned its keep: trained, and beating persistence
    fc = pro["forecast"]
    assert fc is not None and fc["trained"]
    assert fc["skill"] > 0.0
    assert fc["mae_model"] < fc["mae_persistence"]
    # trace accounting: 1 steady-state compile + one per counted bucket
    # promotion, for BOTH proactive kernels (this test owns the dense
    # churn shapes — nothing else compiles them first)
    records = cell["_records"]["proactive"]
    # a promotion landing BEFORE a kernel's first compile folds into it
    # (the elastic convention): the pin is 1 + promotions counted after
    # round 1
    first = records[0].churn["promotions"] if records[0].churn else 0
    final = records[-1].churn["promotions"] if records[-1].churn else 0
    for fn in ("controller_forecast", "controller_decide_proactive_explain"):
        traces = int(
            registry.counter("jax_traces_total", labelnames=("fn",))
            .labels(fn=fn).value
        )
        assert traces == 1 + (final - first), fn
    # every proactive decision re-derives from its recorded explanation
    expls = [e for r in records for e in r.explanations]
    assert expls, "explain plane was off"
    assert all(explanation_consistent(e) for e in expls)
    # predictive rounds actually happened (the gate did not stay shut)
    assert any(r.forecast["mode"] == "predictive" for r in records)


@pytest.mark.slow  # second churn profile at the same scale; the diurnal head-to-head pin stays fast in test_acceptance_proactive_vs_reactive_diurnal above
def test_acceptance_proactive_vs_reactive_deploy_waves(registry):
    """The structural-churn twin of the acceptance soak: deploy-waves
    (services appearing/disappearing) with the same pins, minus the
    exact trace equality (wave promotions may re-land shapes the
    diurnal run already compiled)."""
    out = run_forecast_headtohead(
        profiles=("deploy-waves",),
        logger_factory=lambda: StructuredLogger(name="forecast-h2h-waves"),
        registry=registry,
    )
    cell = out["profiles"]["deploy-waves"]
    pro, rea = cell["proactive"], cell["communication"]
    assert (
        pro["mean_communication_cost"]
        <= rea["mean_communication_cost"] * (1 + 1e-6)
    )
    fc = pro["forecast"]
    assert fc is not None and fc["trained"] and fc["skill"] > 0.0
    records = cell["_records"]["proactive"]
    promotions = max(
        (r.churn["promotions"] for r in records if r.churn), default=0
    )
    traces = int(
        registry.counter("jax_traces_total", labelnames=("fn",))
        .labels(fn="controller_forecast").value
    )
    assert 1 <= traces <= 1 + promotions
    assert all(
        explanation_consistent(e) for r in records for e in r.explanations
    )


def test_forecast_headline_shape_conforms():
    """Satellite: the BENCH_SCENARIO=forecast cell's record shape
    satisfies the parsed-record schema the checked-in bench history is
    held to (scripts/check_bench_schema.py) — the fleet cell's
    convention. The live producer is pinned against the same checker in
    test_bench_forecast_cell_live below."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from scripts.check_bench_schema import check_parsed

    forecast_like = {
        "metric": "device_round_ms_forecast",
        "value": 5.37,
        "unit": "ms",
        "vs_baseline": 18.6,
        "extra": {
            "scenario": "forecast",
            "profile": "diurnal-autoscale",
            "rounds": 30,
            "traces_pinned": True,
            "forecast_skill": 0.05,
            "forecast_skill_tail_mean": 0.04,
        },
    }
    assert check_parsed(forecast_like, "forecast") == []


@pytest.mark.slow  # full powerlaw-scale cell run (~20 s); the record-shape schema pin stays fast in test_forecast_headline_shape_conforms above
def test_bench_forecast_cell_live():
    """The live BENCH_SCENARIO=forecast producer: its actual record
    passes the bench-history schema checker and pins its own trace
    invariant."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import bench
    from scripts.check_bench_schema import check_parsed

    result = bench.bench_forecast(100.0, rounds=4)
    assert check_parsed(result, "bench_forecast") == []
    extra = result["extra"]
    assert extra["scenario"] == "forecast"
    assert extra["traces_pinned"] is True
    assert np.isfinite(extra["forecast_skill"])
    assert np.isfinite(extra["forecast_skill_tail_mean"])
