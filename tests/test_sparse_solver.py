"""Sparse pair-weight path: graph storage round-trips, mass-kernel parity
against the dense matmul, and solver parity / invariants vs the dense
solver (which is the reference implementation)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core import sparsegraph
from kubernetes_rescheduling_tpu.core.sparsegraph import (
    BLOCK_R,
    sparse_pair_comm_cost,
)
from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.ops.sparse_mass import (
    chunk_local_slabs,
    hub_neighbor_mass,
    hub_tile_arrays,
    reference_hub_mass,
    reference_sparse_mass,
    sparse_neighbor_mass,
)
from kubernetes_rescheduling_tpu.solver import (
    GlobalSolverConfig,
    global_assign,
    global_assign_sparse,
)
from kubernetes_rescheduling_tpu.solver.global_solver import exact_comm_cost
from kubernetes_rescheduling_tpu.solver.sparse_solver import sparse_pod_comm_cost


def _random_graph(S, mean_degree, seed, weights=False):
    rng = np.random.default_rng(seed)
    E = int(S * mean_degree / 2)
    src = rng.integers(0, S, size=E)
    dst = rng.integers(0, S, size=E)
    w = rng.integers(1, 5, size=E).astype(np.float64) if weights else np.ones(E)
    return src, dst, w


# ---------------------------------------------------------------- storage


def test_round_trip_dense():
    scn = synthetic_scenario(n_pods=300, n_nodes=8, powerlaw=True, seed=1)
    sg = sparsegraph.from_comm_graph(scn.graph)
    dense = sg.to_dense()
    S = sg.num_services
    np.testing.assert_array_equal(
        np.asarray(dense.adj)[:S, :S], np.asarray(scn.graph.adj)[:S, :S]
    )


def test_workmodel_builder_matches_dense_route():
    wm = mubench_workmodel_c()
    via_wm = sparsegraph.from_workmodel(wm)
    via_dense = sparsegraph.from_comm_graph(wm.comm_graph())
    np.testing.assert_array_equal(
        np.asarray(via_wm.to_dense().adj), np.asarray(via_dense.to_dense().adj)
    )


def test_perm_is_degree_sorted_permutation():
    src, dst, w = _random_graph(700, 4.0, seed=2)
    sg = sparsegraph.from_edges(src, dst, w, 700)
    perm = np.asarray(sg.perm)
    S = sg.num_services
    assert sorted(perm[perm < S].tolist()) == list(range(S))
    inv = np.asarray(sg.inv)
    np.testing.assert_array_equal(perm[inv], np.arange(S))
    # degrees are non-increasing along sorted slots
    adj = np.asarray(sg.to_dense().adj) > 0
    deg = adj.sum(1)
    sorted_deg = deg[perm[perm < S]]
    assert (np.diff(sorted_deg) <= 0).all()


def test_star_graph_becomes_hub_block():
    # one service talks to 300 others: neighbor set exceeds u_reg=128
    S = 512
    src = np.zeros(300, dtype=np.int64)
    dst = np.arange(1, 301, dtype=np.int64)
    sg = sparsegraph.from_edges(src, dst, np.ones(300), S, bu=128, reg_tiles=1)
    assert len(sg.hub_blocks) == 1
    # the hub (degree-300 service 0) landed in the hub block
    assert np.asarray(sg.perm)[sg.hub_blocks[0] * BLOCK_R] == 0
    assert len(sg.regular_blocks) == sg.num_blocks - 1


def test_sparse_comm_cost_matches_dense_exact():
    scn = synthetic_scenario(n_pods=200, n_nodes=10, powerlaw=True, seed=3)
    sg = sparsegraph.from_comm_graph(scn.graph)
    S = sg.num_services
    rng = np.random.default_rng(0)
    for trial in range(3):
        assign_orig = jnp.asarray(rng.integers(0, 10, size=S), jnp.int32)
        rv_orig = jnp.asarray(rng.integers(1, 4, size=S), jnp.float32)
        dense_cost = exact_comm_cost(
            scn.graph.adj[:S, :S], rv_orig, assign_orig
        )
        # map to sorted space
        perm = jnp.clip(sg.perm, 0, S - 1)
        sparse_cost = sparse_pair_comm_cost(
            sg, assign_orig[perm], rv_orig[perm] * (sg.perm < S)
        )
        assert float(dense_cost) == pytest.approx(float(sparse_cost), rel=1e-6)


# ---------------------------------------------------------------- kernels


def _sorted_dense_W(sg, rv_sorted):
    """Dense pair-weight matrix in sorted space, from the COO list."""
    SP = sg.sp
    W = np.zeros((SP, SP), dtype=np.float64)
    src = np.asarray(sg.edges_src)
    dst = np.asarray(sg.edges_dst)
    w = np.asarray(sg.edges_w)
    W[src, dst] = w
    return W * rv_sorted[:, None] * rv_sorted[None, :]


def test_sparse_mass_kernel_matches_dense_matmul():
    src, dst, w = _random_graph(600, 4.0, seed=5, weights=True)
    sg = sparsegraph.from_edges(src, dst, w, 600, bu=128, reg_tiles=8)
    assert not sg.hub_blocks  # wide regular blocks: everything regular
    SP = sg.sp
    N = 16
    rng = np.random.default_rng(1)
    assign = rng.integers(0, N, size=SP).astype(np.int32)
    rv = rng.integers(1, 3, size=SP).astype(np.float32)
    W = _sorted_dense_W(sg, rv)
    blocks = jnp.asarray([2, 0, 1], jnp.int32)
    ids = (
        np.asarray(blocks)[:, None] * BLOCK_R + np.arange(BLOCK_R)[None, :]
    ).reshape(-1)
    # expected: rows of the dense sorted-space W times one-hot occupancy
    X = np.zeros((SP, N))
    X[np.arange(SP), assign] = 1.0
    expected = W[ids] @ X

    rvu = jnp.where(
        sg.u_ids < SP, jnp.asarray(rv)[jnp.clip(sg.u_ids, 0, SP - 1)], 0.0
    )
    toff = jnp.asarray(sg.block_toff, jnp.int32)
    starts = toff[blocks] * sg.bu
    u_c, rvu_c = chunk_local_slabs(sg.u_ids, rvu, starts, sg.u_reg)
    tgt_c = jnp.asarray(assign)[jnp.clip(u_c, 0, SP - 1)]
    kw = dict(num_nodes=N, bu=sg.bu, reg_tiles=sg.reg_tiles)
    got_k = sparse_neighbor_mass(
        sg.w_local, tgt_c, rvu_c, blocks, toff, interpret=True, **kw
    )
    got_x = reference_sparse_mass(sg.w_local, tgt_c, rvu_c, blocks, toff, **kw)
    row_rv = rv[ids][:, None]
    np.testing.assert_allclose(np.asarray(got_k) * row_rv, expected, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_x) * row_rv, expected, rtol=1e-5)
    # kernel and XLA twin agree bit-for-bit (same f32 operation order)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(got_x))


def test_hub_mass_kernel_matches_dense_matmul():
    # star + random background → one hub block with ragged width
    S = 600
    rng = np.random.default_rng(7)
    star_src = np.zeros(260, dtype=np.int64)
    star_dst = np.arange(1, 261, dtype=np.int64)
    bg_src, bg_dst, _ = _random_graph(S, 3.0, seed=8)
    src = np.concatenate([star_src, bg_src])
    dst = np.concatenate([star_dst, bg_dst])
    sg = sparsegraph.from_edges(src, dst, np.ones(len(src)), S, bu=128, reg_tiles=1)
    assert sg.hub_blocks
    SP = sg.sp
    N = 16
    assign = rng.integers(0, N, size=SP).astype(np.int32)
    rv = np.ones(SP, dtype=np.float32)
    W = _sorted_dense_W(sg, rv)
    hub_ids = np.concatenate(
        [np.arange(BLOCK_R) + b * BLOCK_R for b in sg.hub_blocks]
    )
    X = np.zeros((SP, N))
    X[np.arange(SP), assign] = 1.0
    expected = W[hub_ids] @ X

    # group-local slab: static concatenation of the hub blocks' columns
    u_g = jnp.concatenate(
        [
            sg.u_ids[
                sg.block_toff[b] * sg.bu :
                (sg.block_toff[b] + sg.block_ntiles[b]) * sg.bu
            ]
            for b in sg.hub_blocks
        ]
    )
    tgt_l = jnp.asarray(assign)[jnp.clip(u_g, 0, SP - 1)]
    rvu_l = jnp.where(
        u_g < SP, jnp.asarray(rv)[jnp.clip(u_g, 0, SP - 1)], 0.0
    )
    h_col, h_lcol, h_out, h_first = hub_tile_arrays(sg)
    got_k = hub_neighbor_mass(
        sg.w_local, tgt_l, rvu_l, h_col, h_lcol, h_out, h_first,
        num_nodes=N, num_hub_blocks=len(sg.hub_blocks), bu=sg.bu,
        interpret=True,
    )
    got_x = reference_hub_mass(sg, sg.w_local, tgt_l, rvu_l, num_nodes=N)
    np.testing.assert_allclose(np.asarray(got_k), expected, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(got_x))


# ---------------------------------------------------------------- solver


def test_sparse_pod_comm_cost_matches_dense_metric():
    scn = synthetic_scenario(
        n_pods=240, n_nodes=8, powerlaw=True, seed=4, replicas=2
    )
    sg = sparsegraph.from_comm_graph(scn.graph)
    dense = float(communication_cost(scn.state, scn.graph))
    sparse = float(sparse_pod_comm_cost(scn.state, sg))
    assert dense == pytest.approx(sparse, rel=1e-6)


def test_sparse_solver_never_worse_and_improves():
    scn = synthetic_scenario(n_pods=512, n_nodes=8, powerlaw=True, seed=6)
    sg = sparsegraph.from_comm_graph(scn.graph)
    before = float(communication_cost(scn.state, scn.graph))
    new_state, info = global_assign_sparse(
        scn.state, sg, jax.random.PRNGKey(0), GlobalSolverConfig(sweeps=4)
    )
    after = float(communication_cost(new_state, scn.graph))
    assert after <= before
    assert after < before  # plenty of slack on this instance
    assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-4


def test_sparse_solver_with_hub_blocks_never_worse():
    # star-heavy graph → hub pass engaged
    S = 512
    rng = np.random.default_rng(9)
    star_src = np.zeros(300, dtype=np.int64)
    star_dst = np.arange(1, 301, dtype=np.int64)
    bg_src, bg_dst, _ = _random_graph(S, 3.0, seed=10)
    sg = sparsegraph.from_edges(
        np.concatenate([star_src, bg_src]),
        np.concatenate([star_dst, bg_dst]),
        np.ones(300 + len(bg_src)),
        S, bu=128, reg_tiles=1,
    )
    assert sg.hub_blocks
    scn = synthetic_scenario(n_pods=512, n_nodes=8, seed=6)
    dense = sg.to_dense()
    before = float(communication_cost(scn.state, dense))
    new_state, info = global_assign_sparse(
        scn.state, sg, jax.random.PRNGKey(1), GlobalSolverConfig(sweeps=4)
    )
    assert bool(info["hub_pass"])
    after = float(communication_cost(new_state, dense))
    assert after <= before


def test_sparse_solver_bit_parity_with_dense_inline_path():
    """With identity relabeling, no hub blocks, f32 matmuls and integer
    weights, the sparse solver's decisions are BIT-EQUAL to the dense
    solver's inline-mass path: same chunk composition (same key stream),
    same M (exact integer arithmetic), same score/admission kernels."""
    scn = synthetic_scenario(n_pods=1024, n_nodes=8, powerlaw=True, seed=12)
    sg = sparsegraph.from_comm_graph(
        scn.graph, reg_tiles=4, degree_sort=False
    )
    assert not sg.hub_blocks
    # identity relabeling
    np.testing.assert_array_equal(
        np.asarray(sg.perm)[: sg.num_services], np.arange(sg.num_services)
    )
    cfg = GlobalSolverConfig(
        sweeps=3,
        chunk_size=256,
        matmul_dtype="float32",
        fused_epilogue="interpret",
    )
    dense_state, dense_info = global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(3), cfg
    )
    assert bool(dense_info["inline_mass"])  # the path we claim parity with
    sparse_state, sparse_info = global_assign_sparse(
        scn.state, sg, jax.random.PRNGKey(3), cfg
    )
    np.testing.assert_array_equal(
        np.asarray(dense_state.pod_node), np.asarray(sparse_state.pod_node)
    )
    assert float(dense_info["objective_after"]) == pytest.approx(
        float(sparse_info["objective_after"]), rel=1e-6
    )


def test_sparse_solver_respects_capacity():
    from kubernetes_rescheduling_tpu.objectives import capacity_violation

    scn = synthetic_scenario(
        n_pods=512, n_nodes=8, seed=5, node_cpu_cap_m=8000.0,
        imbalance_frac=0.5, powerlaw=True,
    )
    sg = sparsegraph.from_comm_graph(scn.graph)
    v_before = float(capacity_violation(scn.state))
    new_state, _ = global_assign_sparse(
        scn.state, sg, jax.random.PRNGKey(1), GlobalSolverConfig(sweeps=4)
    )
    assert float(capacity_violation(new_state)) <= v_before + 1e-3


def test_sparse_pod_comm_cost_fast_and_slow_branches_agree():
    """The round-5 lax.cond fast path (collapsed placements take the O(E)
    COO cut) must agree with the general pod-level scan on BOTH branch
    predicates: a split placement (slow branch) and its per-service
    collapse (fast branch), each checked against the dense metric."""
    scn = synthetic_scenario(
        n_pods=240, n_nodes=8, powerlaw=True, seed=11, replicas=3
    )
    sg = sparsegraph.from_comm_graph(scn.graph)
    rng = np.random.default_rng(1)
    nodes = rng.integers(0, 8, size=scn.state.num_pods)
    nodes[rng.random(scn.state.num_pods) < 0.1] = -1  # unplaced pods:
    # excluded from the accounting by BOTH branches (and by the metric)
    split = scn.state.replace(pod_node=jnp.asarray(nodes, jnp.int32))
    assert float(communication_cost(split, scn.graph)) == pytest.approx(
        float(sparse_pod_comm_cost(split, sg)), rel=1e-6
    )
    # collapse: every pod moves to its service's first pod's node
    svc_first = np.full(scn.graph.num_services, -1, np.int64)
    pn = np.asarray(split.pod_node)
    ps = np.asarray(split.pod_service)
    for p in range(scn.state.num_pods):
        if svc_first[ps[p]] < 0:
            svc_first[ps[p]] = pn[p]
    collapsed = split.replace(
        pod_node=jnp.asarray(svc_first[ps], jnp.int32)
    )
    assert float(communication_cost(collapsed, scn.graph)) == pytest.approx(
        float(sparse_pod_comm_cost(collapsed, sg)), rel=1e-6
    )
