"""CI twin of ``scripts/check_snapshot_admission.py``: every
``boundary.monitor()`` result the control loops consume passes the
admission guard (``bench/admission.py``) before it can touch device
state — the data sibling of the ``check_boundary_retry`` transport
check."""

import importlib.util
import sys
from pathlib import Path


def _load_checker():
    path = (
        Path(__file__).resolve().parent.parent
        / "scripts"
        / "check_snapshot_admission.py"
    )
    spec = importlib.util.spec_from_file_location(
        "check_snapshot_admission", path
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_snapshot_admission", mod)
    spec.loader.exec_module(mod)
    return mod


def test_control_loops_admit_every_snapshot():
    checker = _load_checker()
    assert checker.violations() == []


def test_checker_catches_an_unadmitted_monitor(tmp_path):
    checker = _load_checker()
    f = tmp_path / "mod.py"
    f.write_text(
        "def monitor_admitted(self):\n"
        "    out = self.boundary.monitor()\n"   # inside the wrapper: legal
        "    return self.guard.admit(out)\n"
        "def preamble(self):\n"
        "    probe = self.boundary.monitor()\n"  # outside: flagged
        "    return probe\n"
    )
    lines = [line for line, _ in checker.find_violations(f)]
    assert lines == [5]


def test_checker_catches_a_wrapper_that_stops_admitting(tmp_path):
    checker = _load_checker()
    f = tmp_path / "mod.py"
    f.write_text(
        "def monitor_admitted(self):\n"
        "    return self.boundary.monitor()\n"  # wrapper lost its admit
    )
    bad = checker.find_violations(f)
    assert len(bad) == 1 and "admit" in bad[0][1]


def test_serving_engine_is_checked_and_wrapper_designated():
    """PR 18 wires the serving plane into the same admission discipline:
    ``serving/engine.py`` is a CHECKED control loop and its
    ``_admitted_snapshot`` is a designated wrapper — the checker config
    itself is pinned so neither can silently fall out."""
    checker = _load_checker()
    assert any(p.name == "engine.py" and p.parent.name == "serving"
               for p in checker.CHECKED)
    assert "_admitted_snapshot" in checker.WRAPPERS


def test_checker_catches_a_raw_monitor_in_a_serving_helper(tmp_path):
    checker = _load_checker()
    f = tmp_path / "engine.py"
    f.write_text(
        "def _admitted_snapshot(self, backend):\n"
        "    return self._guard.admit(backend.monitor())\n"  # legal ingest
        "def refresh_snapshot(self):\n"
        "    self.state = self._backend.monitor()\n"         # flagged: raw
    )
    lines = [line for line, _ in checker.find_violations(f)]
    assert lines == [4]
