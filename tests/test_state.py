"""ClusterState / CommGraph construction and derived quantities."""

import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.core.state import UNASSIGNED, ClusterState, CommGraph


def small_state(**kw):
    return ClusterState.build(
        node_names=["worker1", "worker2", "worker3"],
        node_cpu_cap=[4000.0, 4000.0, 4000.0],
        node_mem_cap=[8e9, 8e9, 8e9],
        pod_services=[0, 1, 2, 0],
        pod_nodes=[0, 0, 1, 2],
        pod_cpu=[100.0, 200.0, 300.0, 50.0],
        pod_mem=[1e6, 2e6, 3e6, 5e5],
        **kw,
    )


class TestBuild:
    def test_shapes_and_masks(self):
        s = small_state(node_capacity=5, pod_capacity=8)
        assert s.num_nodes == 5 and s.num_pods == 8
        assert np.asarray(s.node_valid).sum() == 3
        assert np.asarray(s.pod_valid).sum() == 4
        # padding pods are unassigned
        assert np.all(np.asarray(s.pod_node)[4:] == UNASSIGNED)

    def test_capacity_too_small_raises(self):
        with pytest.raises(ValueError):
            small_state(node_capacity=2)

    def test_lex_rank(self):
        s = ClusterState.build(
            node_names=["worker2", "worker10", "worker1"],
            node_cpu_cap=[1.0, 1.0, 1.0],
            node_mem_cap=[1.0, 1.0, 1.0],
            pod_services=[],
            pod_nodes=[],
            pod_cpu=[],
            pod_mem=[],
        )
        # lexicographic: worker1 < worker10 < worker2
        assert np.asarray(s.node_lex_rank).tolist() == [2, 1, 0]


class TestDerived:
    def test_pod_count(self):
        s = small_state(node_capacity=4, pod_capacity=6)
        assert np.asarray(s.node_pod_count()).tolist() == [2.0, 1.0, 1.0, 0.0]

    def test_cpu_used_and_pct(self):
        s = small_state()
        assert np.asarray(s.node_cpu_used()).tolist() == [300.0, 300.0, 50.0]
        np.testing.assert_allclose(
            np.asarray(s.node_cpu_pct()), [7.5, 7.5, 1.25]
        )

    def test_base_usage_added(self):
        s = small_state().replace(node_base_cpu=jnp.asarray([1000.0, 0.0, 0.0]))
        assert float(s.node_cpu_used()[0]) == 1300.0

    def test_unassigned_pod_not_counted(self):
        s = small_state()
        s = s.replace(pod_node=s.pod_node.at[0].set(UNASSIGNED))
        assert np.asarray(s.node_cpu_used()).tolist() == [200.0, 300.0, 50.0]

    def test_invalid_pod_not_counted(self):
        s = small_state(pod_capacity=6)
        counts = s.node_pod_count()
        assert float(counts.sum()) == 4.0

    def test_service_node_counts(self):
        s = small_state()
        occ = np.asarray(s.service_node_counts(3))
        assert occ.shape == (3, 3)
        assert occ[0].tolist() == [1.0, 0.0, 1.0]  # service 0 on nodes 0 and 2
        assert occ[1].tolist() == [1.0, 0.0, 0.0]
        assert occ[2].tolist() == [0.0, 1.0, 0.0]

    def test_mem_used(self):
        s = small_state()
        assert np.asarray(s.node_mem_used()).tolist() == [3e6, 3e6, 5e5]

    def test_cpu_free(self):
        s = small_state()
        assert np.asarray(s.node_cpu_free()).tolist() == [3700.0, 3700.0, 3950.0]


class TestCommGraph:
    def test_from_relation_symmetrizes(self):
        g = CommGraph.from_relation({"a": ["b"], "b": [], "c": ["a"]})
        adj = np.asarray(g.adj)
        assert adj[0, 1] == adj[1, 0] == 1.0
        assert adj[0, 2] == adj[2, 0] == 1.0
        assert adj[1, 2] == 0.0
        assert np.all(np.diag(adj) == 0)

    def test_padding(self):
        g = CommGraph.from_relation({"a": ["b"], "b": []}, capacity=5)
        assert g.adj.shape == (5, 5)
        assert np.asarray(g.service_valid).tolist() == [True, True, False, False, False]

    def test_roundtrip_to_relation(self):
        rel = {"a": ["b", "c"], "b": ["a"], "c": ["a"]}
        g = CommGraph.from_relation(rel)
        assert g.to_relation() == rel
