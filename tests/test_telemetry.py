"""The unified telemetry layer (ISSUE 1): registry exposition, labeled
series identity, streaming-histogram accuracy, span nesting/export,
retrace accounting, and the controller's one-event-per-round contract."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.harness import make_backend
from kubernetes_rescheduling_tpu.config import RescheduleConfig
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    Tracer,
    get_registry,
    instrument_jit,
    publish_round_telemetry,
    pull,
    run_manifest,
    set_registry,
    set_tracer,
    span,
    timed_call,
    write_manifest,
)
from kubernetes_rescheduling_tpu.telemetry.registry import Histogram
from kubernetes_rescheduling_tpu.telemetry.report import summarize_file
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.profiling import LatencyHistogram


@pytest.fixture
def registry():
    """Fresh process-default registry per test; restores the previous one
    (module-level instrumented jits resolve the default at call time)."""
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def tracer():
    tr = Tracer()
    prev = set_tracer(tr)
    yield tr
    set_tracer(prev)


# ---------------- registry ----------------


def test_exposition_format(registry):
    registry.counter("req_total", "requests", labelnames=("code",)).labels(
        code="200"
    ).inc(3)
    registry.gauge("temp", "temperature").set(1.5)
    h = registry.histogram("lat_s", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = registry.expose()
    lines = text.splitlines()
    assert "# TYPE req_total counter" in lines
    assert 'req_total{code="200"} 3' in lines
    assert "# TYPE temp gauge" in lines
    assert "temp 1.5" in lines
    assert "# TYPE lat_s histogram" in lines
    # buckets are CUMULATIVE and +Inf equals the total count
    assert 'lat_s_bucket{le="0.1"} 1' in lines
    assert 'lat_s_bucket{le="1"} 2' in lines
    assert 'lat_s_bucket{le="+Inf"} 3' in lines
    assert "lat_s_count 3" in lines
    assert text.endswith("\n")


def test_label_escaping(registry):
    registry.counter("c", labelnames=("p",)).labels(p='a"b\\c\nd').inc()
    text = registry.expose()
    assert 'p="a\\"b\\\\c\\nd"' in text


def test_labeled_series_identity(registry):
    fam = registry.counter("hits", "h", labelnames=("algo", "phase"))
    a = fam.labels(algo="global", phase="r2")
    b = fam.labels(phase="r2", algo="global")  # kwarg order must not matter
    assert a is b
    a.inc()
    b.inc(2)
    assert a.value == 3
    other = fam.labels(algo="greedy", phase="r2")
    assert other is not a and other.value == 0


def test_registry_get_or_create_conflicts(registry):
    registry.counter("x_total", "x")
    with pytest.raises(ValueError, match="already registered as counter"):
        registry.gauge("x_total")
    registry.counter("y_total", labelnames=("a",))
    with pytest.raises(ValueError, match="labels"):
        registry.counter("y_total", labelnames=("b",))


def test_counter_monotone(registry):
    c = registry.counter("n_total")
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)


def test_histogram_percentiles_vs_numpy(registry):
    # uniform samples against a fine uniform grid: the interpolated
    # estimate must stay within one bucket width of np.percentile
    buckets = tuple(np.linspace(0.01, 1.0, 100))
    width = buckets[1] - buckets[0]
    h = registry.histogram("u", buckets=buckets)
    rng = np.random.default_rng(0)
    samples = rng.uniform(0.0, 1.0, size=5000)
    for s in samples:
        h.observe(float(s))
    for q in (50, 90, 99):
        est = h.percentile(q)
        true = float(np.percentile(samples, q))
        assert abs(est - true) <= width + 1e-9, (q, est, true)
    # clamped to the observed range whatever the interpolation says
    assert h.percentile(0) >= samples.min() - 1e-12
    assert h.percentile(100) <= samples.max() + 1e-12


def test_latency_histogram_keeps_summary_schema():
    h = LatencyHistogram()
    assert h.summary() == {"count": 0}
    for v in (0.001, 0.002, 0.004, 0.008):
        h.add(v)
    s = h.summary()
    assert s["count"] == 4
    assert s["mean_ms"] == pytest.approx(3.75, rel=1e-6)
    assert s["max_ms"] == pytest.approx(8.0, rel=1e-6)
    assert s["decisions_per_sec"] == pytest.approx(1 / 0.00375, rel=1e-6)
    # streaming now: no unbounded sample list behind the API
    assert not hasattr(h, "samples_s")
    assert isinstance(h, Histogram)


def test_jsonl_dump_and_report_roundtrip(registry, tmp_path):
    registry.counter("rounds_total", labelnames=("algorithm",)).labels(
        algorithm="global"
    ).inc(7)
    registry.histogram("d_s", buckets=(0.1, 1.0)).observe(0.2)
    out = tmp_path / "m.jsonl"
    registry.dump_jsonl(out)
    registry.dump_jsonl(out)  # appended snapshots: the report takes the last
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    assert {r["metric"] for r in recs} == {"rounds_total", "d_s"}
    text = summarize_file(out)
    assert "rounds_total{algorithm=global} = 7" in text
    assert "d_s" in text and "count=1" in text


# ---------------- spans ----------------


def test_span_nesting_and_chrome_roundtrip(registry, tracer, tmp_path):
    with span("outer", kind="test"):
        with span("inner"):
            pass
    out = tmp_path / "trace.json"
    tracer.export_chrome(out)
    doc = json.loads(out.read_text())
    evs = {e["name"]: e for e in doc["traceEvents"]}
    assert set(evs) == {"outer", "inner"}
    outer, inner = evs["outer"], evs["inner"]
    assert outer["ph"] == inner["ph"] == "X"
    assert outer["args"]["depth"] == 0 and inner["args"]["depth"] == 1
    assert outer["args"]["kind"] == "test"
    # the child interval nests inside the parent (µs; tiny clock slack)
    assert inner["ts"] >= outer["ts"] - 1.0
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    # span durations also land in the registry
    fam = registry.histogram("span_seconds", labelnames=("span",))
    assert fam.labels(span="outer").count == 1
    assert fam.labels(span="inner").count == 1


def test_tracer_bounded(registry):
    tr = Tracer(registry=registry, max_events=3)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.events) == 3
    assert tr.dropped == 2


# ---------------- accounting ----------------


def test_instrument_jit_counts_exactly_one_steady_state_trace(registry):
    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        return x * 2.0

    g = instrument_jit(f, name="steady")
    for i in range(4):
        jax.block_until_ready(g(jnp.arange(7.0) + i))
    fam = registry.counter("jax_traces_total", labelnames=("fn",))
    assert fam.labels(fn="steady").value == 1
    # the body runs once for the real trace and once more for the
    # first-compile cost capture (the AOT lower of the raw fn) — the
    # capture run is NOT a counted trace, and never repeats: cache hits
    # re-dispatch the compiled kernel without touching Python
    assert calls["n"] == 2
    assert (
        registry.counter("jax_calls_total", labelnames=("fn",))
        .labels(fn="steady")
        .value
        == 4
    )
    captures = registry.counter("jax_cost_captures_total", labelnames=("fn",))
    assert captures.labels(fn="steady").value == 1


def test_instrument_jit_capture_disabled_keeps_single_body_run(
    registry, monkeypatch
):
    """KRT_COST_CAPTURE=0 restores the historical contract exactly: one
    Python-body run, no extra AOT compile, no cost gauges."""
    monkeypatch.setenv("KRT_COST_CAPTURE", "0")
    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        return x + 1.0

    g = instrument_jit(f, name="steady_nocap")
    for i in range(3):
        jax.block_until_ready(g(jnp.arange(5.0) + i))
    assert calls["n"] == 1
    from kubernetes_rescheduling_tpu.telemetry.costmodel import get_costbook

    assert get_costbook().get("steady_nocap") is None
    assert 'jax_cost_flops{fn="steady_nocap"}' not in registry.expose()


def test_instrument_jit_catches_shape_polymorphism(registry):
    def f(x):
        return jnp.sum(x)

    g = instrument_jit(f, name="poly")
    # deliberately shape-polymorphic: every length is a fresh signature —
    # the silent-retrace failure mode becomes a visible count
    for n in (2, 3, 4):
        jax.block_until_ready(g(jnp.zeros((n,))))
    fam = registry.counter("jax_traces_total", labelnames=("fn",))
    assert fam.labels(fn="poly").value == 3
    assert g.traces() == 3
    # compile wall-time got attributed to every tracing call
    hist = registry.histogram(
        "jax_compile_seconds", labelnames=("fn",)
    ).labels(fn="poly")
    assert hist.count == 3


def test_pull_counts_transfers(registry):
    out = pull(jnp.arange(3), site="test_site")
    assert isinstance(out, np.ndarray)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="test_site").value == 1


def test_timed_call_and_count_reconcile(registry):
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=["a", "b"],
        seed=0,
        load=LoadModel(),
    )
    backend.monitor()
    from kubernetes_rescheduling_tpu.backends.base import MoveRequest

    svc = backend.workmodel.names[0]
    assert backend.apply_move(
        MoveRequest(service=svc, target_node="b", mechanism="nodeName")
    )
    calls = registry.counter(
        "backend_calls_total", labelnames=("backend", "call")
    )
    assert calls.labels(backend="sim", call="monitor").value == 1
    assert calls.labels(backend="sim", call="apply_move").value == 1
    lat = registry.histogram(
        "backend_call_seconds", labelnames=("backend", "call")
    ).labels(backend="sim", call="apply_move")
    assert lat.count == 1
    rec = registry.counter("backend_reconciles_total", labelnames=("backend",))
    assert rec.labels(backend="sim").value == 1
    pods = registry.counter(
        "backend_pods_restarted_total", labelnames=("backend",)
    )
    assert pods.labels(backend="sim").value >= 1


def test_publish_round_telemetry(registry):
    from kubernetes_rescheduling_tpu.solver import run_rounds

    backend = make_backend("mubench", 0)
    backend.inject_imbalance(backend.node_names[0])
    state = backend.monitor()
    _, tel = run_rounds(
        state, backend.comm_graph(), jnp.int32(4), jax.random.PRNGKey(0),
        rounds=4,
    )
    out = publish_round_telemetry(tel, algorithm="communication")
    assert out["rounds"] == 4
    fam = registry.counter("rounds_total", labelnames=("algorithm",))
    assert fam.labels(algorithm="communication").value == 4
    assert registry.gauge(
        "communication_cost", labelnames=("algorithm",)
    ).labels(algorithm="communication").value == pytest.approx(
        out["communication_cost"]
    )


# ---------------- controller integration ----------------


def _controller_backend(n_nodes=5):
    """Deliberately UNIQUE shapes (5 nodes vs the 3-node mubench used
    elsewhere) so the module-level decision kernel must compile fresh in
    this test — the exactly-one-trace assertion cannot be satisfied by a
    stale cache entry from another test."""
    backend = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"w{i}" for i in range(n_nodes)],
        node_cpu_cap_m=20_000.0,
        seed=0,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    backend.inject_imbalance(backend.node_names[0])
    return backend


def test_run_controller_one_round_event_and_one_compile(registry, tracer):
    rounds = 4
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=rounds,
        sleep_after_action_s=0.0,
    )
    result = run_controller(_controller_backend(), cfg, logger=logger)
    assert len(result.rounds) == rounds
    round_events = [r for r in logger.records if r["event"] == "round"]
    assert len(round_events) == rounds
    fam = registry.counter("rounds_total", labelnames=("algorithm",))
    assert fam.labels(algorithm="communication").value == rounds
    # THE acceptance invariant: the steady-state loop compiles its
    # decision kernel exactly once — a second trace means every round
    # paid a recompile. With a logger attached the loop runs the EXPLAIN
    # twin of the kernel; the same invariant applies to it.
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="controller_decide_explain").value == 1
    calls = registry.counter("jax_calls_total", labelnames=("fn",))
    assert calls.labels(fn="controller_decide_explain").value == rounds
    # spans cover every round
    names = [e.name for e in tracer.events]
    assert names.count("controller/round") == rounds
    assert names.count("backend/monitor") == rounds
    hist = registry.histogram(
        "decision_seconds", labelnames=("algorithm",)
    ).labels(algorithm="communication")
    assert hist.count == rounds
    # the bare loop (no logger/ops listening) keeps the historical plain
    # kernel, with the same exactly-one-trace contract — fresh 6-node
    # shapes so a cache hit cannot fake the assertion
    bare = run_controller(_controller_backend(n_nodes=6), cfg)
    assert len(bare.rounds) == rounds
    assert traces.labels(fn="controller_decide").value == 1
    assert calls.labels(fn="controller_decide").value == rounds


@pytest.mark.slow  # the solver before/after objective surfacing stays
# pinned fast by test_observability.py::
# test_global_round_explanation_scores_match_wave_selection (the same
# _pull_solver_objectives fields on the explanation record of a global
# controller round); this is the heavy gauge/transfer-count variant
def test_run_controller_global_objectives_surface(registry):
    rounds = 2
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="global",
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        balance_weight=0.5,
    )
    result = run_controller(_controller_backend(), cfg, logger=logger)
    rec = result.rounds[0]
    # solve_with_restarts reports the adopted objective; the incoming
    # objective is only present on solver paths that compute it — the
    # pull surfaces whatever the info dict carries without inventing keys
    assert rec.objective_after is not None
    round_events = [r for r in logger.records if r["event"] == "round"]
    assert len(round_events) == rounds
    assert round_events[0]["objective_after"] == pytest.approx(
        rec.objective_after
    )
    # the solver objectives ride the round's SINGLE round-end bundle
    # transfer (bench/round_end.py) — no separate counted pull remains
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == rounds
    assert fam.labels(site="solver_objectives").value == 0


# ---------------- logger ring buffer ----------------


def test_structured_logger_ring_buffer(tmp_path):
    path = tmp_path / "log.jsonl"
    logger = StructuredLogger(name="t", path=path, max_records=8)
    for i in range(20):
        logger.info("tick", i=i)
    recs = logger.records
    assert len(recs) == 8  # in-memory view capped...
    assert [r["i"] for r in recs] == list(range(12, 20))  # ...newest win
    # ...but the file sink saw every event
    assert len(path.read_text().splitlines()) == 20


# ---------------- manifest ----------------


def test_manifest_contents(tmp_path):
    m = write_manifest(tmp_path / "run.manifest.json", {"algo": "global"})
    on_disk = json.loads((tmp_path / "run.manifest.json").read_text())
    assert on_disk["config"] == {"algo": "global"}
    for key in ("timestamp", "argv", "python", "platform", "jax", "git"):
        assert key in on_disk, key
    # jax was imported by this test process, so devices are inventoried
    assert m["jax"]["imported"] is True
    assert m["jax"]["device_count"] >= 1
    assert m["git"] is None or "rev" in m["git"]
    text = summarize_file(tmp_path / "run.manifest.json")
    assert "jax" in text


def test_manifest_without_jax_in_modules(monkeypatch):
    import sys

    real = sys.modules.get("jax")
    monkeypatch.setitem(sys.modules, "jax", None)
    try:
        m = run_manifest()
    finally:
        monkeypatch.setitem(sys.modules, "jax", real)
    assert m["jax"] == {"imported": False}


# ---------------- CLI end-to-end (the acceptance artifact set) ----------------


def test_cli_bench_writes_telemetry_artifacts(
    registry, tracer, tmp_path, capsys
):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    metrics = tmp_path / "m.jsonl"
    trace = tmp_path / "t.json"
    rc = cli_main(
        [
            "bench",
            "--algorithms", "communication",
            "--repeats", "1",
            "--rounds", "2",
            "--out", str(tmp_path / "result"),
            "--metrics-out", str(metrics),
            "--trace-out", str(trace),
        ]
    )
    assert rc == 0
    capsys.readouterr()

    # metrics JSONL: one record per series, rounds counted
    recs = [json.loads(l) for l in metrics.read_text().splitlines()]
    by_name = {}
    for r in recs:
        by_name.setdefault(r["metric"], []).append(r)
    rounds_rec = [
        r
        for r in by_name["rounds_total"]
        if r["labels"] == {"algorithm": "communication"}
    ]
    assert rounds_rec and rounds_rec[-1]["value"] == 2

    # Prometheus text exposition next to it
    prom = tmp_path / "m.prom"
    text = prom.read_text()
    assert "# TYPE rounds_total counter" in text
    assert "# TYPE backend_call_seconds histogram" in text
    assert 'rounds_total{algorithm="communication"} 2' in text

    # Perfetto-loadable Chrome trace with the controller's spans
    doc = json.loads(trace.read_text())
    names = [e["name"] for e in doc["traceEvents"]]
    assert names.count("controller/round") == 2
    assert "bench/run" in names

    # run manifest: what ran, from which commit, on which devices
    manifest = json.loads((tmp_path / "m.manifest.json").read_text())
    assert manifest["config"]["command"] == "bench"
    assert manifest["config"]["rounds"] == 2
    assert manifest["jax"]["imported"] is True

    # session-level manifest from the harness itself
    sessions = list((tmp_path / "result").glob("session_*"))
    assert len(sessions) == 1
    assert (sessions[0] / "manifest.json").is_file()
    assert (sessions[0] / "communication" / "run_1" / "metrics.jsonl").is_file()


def test_cli_telemetry_report(registry, tracer, tmp_path, capsys):
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    registry.counter("rounds_total", labelnames=("algorithm",)).labels(
        algorithm="global"
    ).inc(3)
    metrics = tmp_path / "m.jsonl"
    registry.dump_jsonl(metrics)
    log = tmp_path / "log.jsonl"
    lg = StructuredLogger(name="t", path=log)
    lg.info("round", round=0, moved=True, communication_cost=5.0,
            decision_latency_s=0.01)
    lg.info("round", round=1, moved=False, communication_cost=4.0,
            decision_latency_s=0.02)
    manifest = tmp_path / "m.manifest.json"
    write_manifest(manifest, {"command": "bench"})

    rc = cli_main(["telemetry", str(metrics), str(log), str(manifest)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "rounds_total{algorithm=global} = 3" in out
    assert "rounds: 2" in out
    assert "communication_cost: 5.00 -> 4.00" in out
    assert "jax" in out
