"""Communication-cost attribution & topology plane (ISSUE 5): the
on-device decomposition kernel, the host-side attribution record and its
sum-consistency invariant, the placement timeline / move provenance
tracker, the cardinality-bounded topology gauges, the attribution_drift
watchdog rule, and the `telemetry topo` CLI — plus the seeded-soak
acceptance at the bottom (every executed round's attribution re-derives
the recorded cost scalar; exactly one extra device transfer per round;
exactly one steady-state trace)."""

import contextlib
import io
import json
import types
from pathlib import Path

import jax
import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.sim import LoadModel, SimBackend
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.harness import make_backend, run_chaos_soak
from kubernetes_rescheduling_tpu.config import ObsConfig, RescheduleConfig
from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives.metrics import (
    communication_cost,
    communication_cost_attribution,
    node_pair_cost_matrix,
)
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.telemetry.attribution import (
    PlacementTimeline,
    attribution_consistent,
    check_attribution,
    decode_attribution,
    get_attribution_book,
    publish_attribution,
)
from kubernetes_rescheduling_tpu.telemetry.watchdog import SLORules, Watchdog
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def _two_svc_state():
    """svc0: 2 replicas on n0 + 1 on n1 (split); svc1: 1 replica on n2."""
    graph = CommGraph.from_relation({"a": ["b"], "b": []}, names=["a", "b"])
    state = ClusterState.build(
        node_names=["n0", "n1", "n2"],
        node_cpu_cap=[1000.0] * 3,
        node_mem_cap=[1e9] * 3,
        pod_services=[0, 0, 0, 1],
        pod_nodes=[0, 0, 1, 2],
        pod_cpu=[10.0] * 4,
        pod_mem=[1.0] * 4,
    )
    return state, graph


# ---------------- the device kernel ----------------


def test_node_pair_matrix_decomposes_the_scalar():
    backend = make_backend("mubench", seed=2)
    state = backend.monitor()  # spread placement: nonzero cross cost
    graph = backend.comm_graph()
    cost = float(communication_cost(state, graph))
    m = np.asarray(node_pair_cost_matrix(state, graph))
    assert cost > 0
    assert 0.5 * m.sum() == pytest.approx(cost, rel=1e-5)
    assert np.allclose(np.diag(m), 0.0)
    assert np.allclose(m, m.T)  # undirected graph -> symmetric collapse


def test_attribution_bundle_matches_numpy_recompute():
    backend = make_backend("mubench", seed=2)
    state = backend.monitor()
    graph = backend.comm_graph()
    k = 6
    bundle = np.asarray(
        communication_cost_attribution(state, graph, top_k=k)
    )
    attr = decode_attribution(
        bundle,
        node_names=state.node_names,
        service_names=graph.names,
        top_k=k,
        num_nodes=state.num_nodes,
        num_services=graph.num_services,
    )
    cost = float(communication_cost(state, graph))
    assert attr["total"] == pytest.approx(cost, rel=1e-5)
    assert attribution_consistent(attr, communication_cost=cost)

    # numpy oracle: the per-service-pair contribution matrix
    num_s = graph.num_services
    occ = np.asarray(state.service_node_counts(num_s))
    sv = np.asarray(graph.service_valid)
    adj = np.asarray(graph.adj) * sv[:, None] * sv[None, :]
    tot = occ.sum(axis=1)
    contrib = adj * (tot[:, None] * tot[None, :] - occ @ occ.T)
    upper = [
        (contrib[i, j], i, j)
        for i in range(num_s)
        for j in range(i + 1, num_s)
        if contrib[i, j] > 0
    ]
    upper.sort(reverse=True)
    got = [
        (e["cost"], e["src_service"], e["dst_service"])
        for e in attr["edges"]
    ]
    want_costs = sorted((c for c, _, _ in upper), reverse=True)[: len(got)]
    assert [c for c, _, _ in got] == pytest.approx(want_costs)
    # tail carries everything outside the top-k
    assert attr["tail"] == pytest.approx(
        sum(c for c, _, _ in upper) - sum(c for c, _, _ in got), abs=1e-3
    )


def test_attribution_dominant_node_pair_with_split_replicas():
    state, graph = _two_svc_state()
    bundle = np.asarray(
        communication_cost_attribution(state, graph, top_k=2)
    )
    attr = decode_attribution(
        bundle,
        node_names=state.node_names,
        service_names=graph.names,
        top_k=2,
        num_nodes=3,
        num_services=2,
    )
    # all 3 a-replicas talk cross-node to b@n2: cost = 3; the dominant
    # node pair is (n0, n2) — 2 of the 3 communicating replica pairs
    assert attr["total"] == pytest.approx(3.0)
    [edge] = attr["edges"]
    assert {edge["src_service"], edge["dst_service"]} == {"a", "b"}
    assert {edge["src_node"], edge["dst_node"]} == {"n0", "n2"}
    assert edge["cost"] == pytest.approx(3.0)
    # ingress/egress each sum back to the scalar (half-weighted collapse)
    assert sum(attr["ingress"].values()) == pytest.approx(3.0)
    assert sum(attr["egress"].values()) == pytest.approx(3.0)


def test_attribution_consistency_catches_tampering():
    state, graph = _two_svc_state()
    bundle = np.asarray(
        communication_cost_attribution(state, graph, top_k=2)
    )
    attr = decode_attribution(
        bundle,
        node_names=state.node_names,
        service_names=graph.names,
        top_k=2,
        num_nodes=3,
        num_services=2,
    )
    assert attribution_consistent(attr)
    bad = json.loads(json.dumps(attr))
    bad["edges"][0]["cost"] += 1.0  # edges no longer sum to total
    assert not attribution_consistent(bad)
    bad2 = json.loads(json.dumps(attr))
    bad2["ingress"]["n0"] += 5.0
    assert not attribution_consistent(bad2)
    # a recorded scalar the attribution cannot reproduce fails too
    assert not attribution_consistent(attr, communication_cost=99.0)
    # and provenance: per-move edge deltas must sum to the move's delta
    withmoves = json.loads(json.dumps(attr))
    withmoves["moves"] = [
        {"service": "a", "cost_delta": -2.0, "edges": [{"peer": "b", "delta": -2.0}]}
    ]
    withmoves["objective_delta"] = -2.0
    assert attribution_consistent(withmoves)
    withmoves["moves"][0]["edges"][0]["delta"] = 1.0
    assert not attribution_consistent(withmoves)


# ---------------- placement timeline / move provenance ----------------


def test_timeline_move_deltas_telescope():
    state, graph = _two_svc_state()
    tl = PlacementTimeline()
    tl.bind(state, graph)
    before = tl._model_total()
    assert before == pytest.approx(3.0)
    block = tl.observe_round(1, [("a", "n2")])  # co-locate with b
    [mv] = block["moves"]
    assert mv["from"] == "n0" and mv["to"] == "n2"
    assert mv["cost_delta"] == pytest.approx(-3.0)
    assert sum(e["delta"] for e in mv["edges"]) == pytest.approx(-3.0)
    assert block["objective_delta"] == pytest.approx(-3.0)
    assert block["model_total"] == pytest.approx(0.0)
    # second round: move b away again — deltas keep telescoping
    block2 = tl.observe_round(2, [("b", "n1")])
    assert block2["objective_delta"] == pytest.approx(3.0)
    assert block2["model_total"] == pytest.approx(3.0)
    # residency recorded both hops
    assert [n for _, n in tl.residency["a"]] == ["n0", "n2"]
    assert tl.render_residency()


def test_timeline_pod_level_and_unknown_names_are_safe():
    state, graph = _two_svc_state()
    tl = PlacementTimeline()
    tl.bind(state, graph)
    block = tl.observe_round(1, [("a", "n1")], pod_level=True)
    assert block["objective_delta"] is None
    assert block["moves"][0]["cost_delta"] is None
    # unknown service/node: residency tracked, delta skipped, no crash
    block2 = tl.observe_round(2, [("ghost", "nowhere")])
    assert block2["moves"][0]["cost_delta"] is None


# ---------------- gauges: cardinality-bounded publication ----------------


def _fake_attr():
    return {
        "total": 10.0,
        "tail": 0.0,
        "edges": [
            {"src_service": "a", "dst_service": "b", "src_node": "n0",
             "dst_node": "n1", "cost": 6.0},
            {"src_service": "a", "dst_service": "c", "src_node": "n0",
             "dst_node": "n2", "cost": 4.0},
        ],
        "node_pairs": [["n0", "n1", 12.0], ["n1", "n0", 12.0],
                       ["n0", "n2", 8.0], ["n2", "n0", 8.0]],
        "ingress": {"n0": 5.0, "n1": 3.0, "n2": 2.0},
        "egress": {"n0": 5.0, "n1": 3.0, "n2": 2.0},
    }


def test_publish_attribution_zeroes_stale_pairs(registry):
    publish_attribution(registry, _fake_attr(), top_k=4)
    pair = registry.gauge("comm_cost_node_pair", labelnames=("src", "dst"))
    # UNORDERED publication: one child per pair, full cost — so an
    # untruncated family sums to the scalar (12 + 8 = 2 * total's 10...
    # the fake's numbers are synthetic; the sum property is pinned on
    # real rounds in the soak acceptance)
    assert pair.labels(src="n0", dst="n1").value == pytest.approx(12.0)
    assert pair.labels(src="n1", dst="n0").value == 0.0  # never published
    # next round: the n0-n1 pair vanishes — its gauge must read 0, not 12
    attr2 = _fake_attr()
    attr2["node_pairs"] = [["n0", "n2", 20.0], ["n2", "n0", 20.0]]
    publish_attribution(registry, attr2, top_k=4)
    assert pair.labels(src="n0", dst="n1").value == 0.0
    assert pair.labels(src="n0", dst="n2").value == pytest.approx(20.0)
    # edge ranks are fixed-cardinality: exactly top_k children ever
    edge = registry.gauge("comm_cost_edge_topk", labelnames=("rank",))
    assert len(edge._children) == 4
    assert edge.labels(rank="0").value == pytest.approx(6.0)
    assert edge.labels(rank="3").value == 0.0


# ---------------- watchdog: attribution_drift ----------------


def _rec(attr):
    return types.SimpleNamespace(
        decision_latency_s=0.01, communication_cost=attr["total"],
        attribution=attr,
    )


def test_watchdog_attribution_drift_fires_and_recovers(registry):
    logger = StructuredLogger(name="t")
    wd = Watchdog(
        SLORules(attribution_drift_frac=0.5, max_retraces=0),
        registry=registry, logger=logger,
    )
    balanced = _fake_attr()  # top edge 6/10 > 0.5 -> fires
    assert any(
        v["rule"] == "attribution_drift" for v in wd.observe_round(_rec(balanced))
    )
    assert not wd.healthy
    fam = registry.counter("slo_violations_total", labelnames=("rule",))
    assert fam.labels(rule="attribution_drift").value == 1
    ok = _fake_attr()
    ok["edges"][0]["cost"] = 4.0  # 4/10 <= 0.5 -> recovers
    wd.observe_round(_rec(ok))
    assert wd.healthy
    events = [r["event"] for r in logger.records]
    assert "slo_violation" in events and "slo_recovered" in events


def test_watchdog_drift_rule_off_by_default(registry):
    wd = Watchdog(SLORules(max_retraces=0), registry=registry)
    wd.observe_round(_rec(_fake_attr()))
    assert wd.healthy


def test_config_attribution_knobs(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "algorithm = 'communication'\n"
        "[obs]\n"
        "attribution = false\n"
        "attribution_top_k = 4\n"
        "attribution_drift_frac = 0.6\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.obs.attribution is False
    assert cfg.obs.attribution_top_k == 4
    assert cfg.obs.attribution_drift_frac == 0.6
    with pytest.raises(ValueError):
        ObsConfig(attribution_top_k=0).validate()
    with pytest.raises(ValueError):
        ObsConfig(attribution_drift_frac=1.5).validate()


# ---------------- controller integration + acceptance ----------------


def _backend(n_nodes):
    """UNIQUE shapes per test (node count) so the exactly-one-trace pin
    cannot be satisfied — or defeated — by another test's cache entry."""
    b = SimBackend(
        workmodel=mubench_workmodel_c(),
        node_names=[f"w{i}" for i in range(n_nodes)],
        node_cpu_cap_m=20_000.0,
        seed=0,
        load=LoadModel(entry_rps=100.0, cost_per_req_m=8.0, idle_m=50.0),
    )
    b.inject_imbalance(b.node_names[0])
    return b


def test_controller_attribution_soak_acceptance(registry, tmp_path):
    """ISSUE 5 acceptance (deterministic half): a seeded greedy soak
    records attribution on every round; per-edge contributions re-derive
    the recorded cost scalar and per-move deltas the objective delta;
    the plane costs exactly ONE device transfer per round and ONE
    steady-state trace; gauges stay inside their cardinality budget;
    `telemetry topo` renders the rounds end-to-end."""
    rounds = 6
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=rounds,
        sleep_after_action_s=0.0, seed=1,
        obs=ObsConfig(attribution_top_k=5),
    )
    get_attribution_book().clear()
    result = run_controller(_backend(4), cfg, logger=logger)
    assert len(result.rounds) == rounds

    for rec in result.rounds:
        attr = rec.attribution
        assert attr is not None
        assert attribution_consistent(
            attr, communication_cost=rec.communication_cost
        ), f"round {rec.round} attribution does not re-derive its scalar"
        # rounds.jsonl carries it (as_dict is the sink's record shape)
        assert rec.as_dict()["attribution"]["total"] == attr["total"]
    checked, bad = check_attribution([r.as_dict() for r in result.rounds])
    assert checked == rounds and bad == []

    # exactly ONE round-end transfer per executed round: the attribution
    # bundle rides the same pull as the cost/load-std pair and the
    # explain bundles (bench/round_end.py) — no separate attribution pull
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == rounds
    assert fam.labels(site="attribution").value == 0
    # exactly one steady-state trace of the round-end kernel; it
    # dispatches once per fresh snapshot (the startup snapshot's bundle
    # is the degraded-close fallback and is never pulled)
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="controller_round_end").value == 1
    calls = registry.counter("jax_calls_total", labelnames=("fn",))
    assert calls.labels(fn="controller_round_end").value == rounds + 1

    # cardinality budget: unordered node pairs <= N(N-1)/2, per-node
    # <= N, ranks == k
    n = 4
    pair = registry.gauge("comm_cost_node_pair", labelnames=("src", "dst"))
    assert 0 < len(pair._children) <= n * (n - 1) // 2
    for name in ("comm_cost_node_ingress", "comm_cost_node_egress"):
        assert 0 < len(registry.gauge(name, labelnames=("node",))._children) <= n
    edge = registry.gauge("comm_cost_edge_topk", labelnames=("rank",))
    assert len(edge._children) == 5

    # the process-global book carries the latest summary (manifest rider)
    book = get_attribution_book().as_dict()
    assert book["communication"]["round"] == rounds
    assert book["communication"]["total"] == pytest.approx(
        result.rounds[-1].attribution["total"]
    )

    # telemetry topo renders the rounds end-to-end
    from kubernetes_rescheduling_tpu.cli import main as cli_main

    p = tmp_path / "rounds.jsonl"
    p.write_text(
        "".join(
            json.dumps(r.as_dict(), default=float) + "\n"
            for r in result.rounds
        )
    )
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        assert cli_main(["telemetry", "topo", str(p)]) == 0
    text = out.getvalue()
    assert "edge attribution" in text
    assert "node-pair heatmap" in text
    assert "move provenance" in text
    assert f"{rounds}/{rounds} rounds re-derive" in text
    assert "INCONSISTENT" not in text


@pytest.mark.slow  # heavy global-solve variant: attribution consistency
# + move provenance stay pinned fast by
# test_controller_attribution_soak_acceptance above (greedy rounds, same
# invariants incl. delta telescoping), and global-round candidate/gain
# consistency by
# test_observability.test_global_round_explanation_scores_match_wave_selection
def test_global_round_attribution_and_provenance(registry):
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="global", max_rounds=2, sleep_after_action_s=0.0,
        seed=3, balance_weight=0.5,
    )
    result = run_controller(_backend(5), cfg, logger=logger)
    moved = [r for r in result.rounds if r.applied_moves]
    assert moved, "global rounds should land moves on the piled-up cluster"
    for rec in result.rounds:
        attr = rec.attribution
        assert attribution_consistent(
            attr, communication_cost=rec.communication_cost
        )
        assert len(attr["moves"]) == len(rec.applied_moves)
        if attr["moves"]:
            assert attr["objective_delta"] == pytest.approx(
                sum(m["cost_delta"] for m in attr["moves"]), abs=1e-3
            )


def test_bare_loop_records_no_attribution(registry):
    """No logger/ops attached: the historical loop — no attribution
    records, no extra transfers, no attribution kernel compile."""
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
    )
    result = run_controller(_backend(6), cfg)
    assert all(r.attribution is None for r in result.rounds)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="attribution").value == 0
    traces = registry.counter("jax_traces_total", labelnames=("fn",))
    assert traces.labels(fn="controller_attribution").value == 0


def test_attribution_off_switch(registry):
    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        obs=ObsConfig(attribution=False),
    )
    result = run_controller(_backend(7), cfg, logger=logger)
    assert all(r.attribution is None for r in result.rounds)


def test_chaos_soak_attribution_stays_consistent(registry):
    """The seeded-soak half of the acceptance: under injected faults
    (degraded rounds, failed moves, breaker churn) every EXECUTED round
    still records a sum-consistent attribution, and the per-round
    transfer pin holds (skipped rounds pull nothing)."""
    from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy

    logger = StructuredLogger(name="t")
    report = run_chaos_soak(
        profile="soak", rounds=20, seed=1, chaos_seed=0,
        retry=RetryPolicy(max_attempts=1),
        max_consecutive_failures=3,
        logger=logger, registry=registry,
    )
    assert report["records"] + report["skipped_rounds"] == 20
    # one round-end bundle per EXECUTED round (skipped rounds pull
    # nothing; degraded rounds reuse cached metrics but still flush
    # their fresh explain bundle — one transfer either way)
    fam = registry.counter("device_transfers_total", labelnames=("site",))
    assert fam.labels(site="round_end").value == report["records"]
    assert fam.labels(site="attribution").value == 0


def test_flight_recorder_bundle_carries_attribution(registry, tmp_path):
    from kubernetes_rescheduling_tpu.telemetry import FlightRecorder

    logger = StructuredLogger(name="t")
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=3, sleep_after_action_s=0.0,
        seed=1,
    )
    fr = FlightRecorder(capacity=8, bundle_dir=tmp_path, registry=registry)
    result = run_controller(_backend(8), cfg, logger=logger)
    for r in result.rounds:
        fr.record_round(round=r.round, digest="x", record=r.as_dict())
    bundle = json.loads(fr.dump("crash", error="boom").read_text())
    checked, bad = check_attribution(bundle["rounds"])
    assert checked == 3 and bad == []
    # the book rode along (and the manifest carries it too)
    assert bundle["attribution"]
    assert bundle["manifest"]["attribution"]
    # telemetry bundle prints the attribution verdict
    from kubernetes_rescheduling_tpu.telemetry.report import (
        report_bundle,
        report_topo,
    )

    text = report_bundle([str(fr.dumps[-1])])
    assert "attribution: 3 recorded, 3 sum-consistent" in text
    # ... and telemetry topo renders the bundle end-to-end
    topo = report_topo([str(fr.dumps[-1])])
    assert "edge attribution" in topo and "3/3 rounds re-derive" in topo
