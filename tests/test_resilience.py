"""Resilience layer: retry policy, chaos backend, circuit breaker,
degraded-mode controller, crash-safe checkpoints — ISSUE 2's surface.

The acceptance soak test at the bottom runs ≥30 rounds under the seeded
"soak" fault profile (monitor failures + move timeouts + node flap) and
pins the invariants: the controller never raises, the breaker opens and
re-closes, no round is silently lost, and every injected fault shows up
in the telemetry registry.
"""

import dataclasses
import json
import random

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.backends.chaos import (
    PROFILES,
    ChaosBackend,
    ChaosError,
    ChaosProfile,
    with_chaos,
)
from kubernetes_rescheduling_tpu.bench.boundary import (
    BoundaryClient,
    CircuitBreaker,
)
from kubernetes_rescheduling_tpu.bench.controller import run_controller
from kubernetes_rescheduling_tpu.bench.harness import make_backend, run_chaos_soak
from kubernetes_rescheduling_tpu.config import ChaosConfig, RescheduleConfig
from kubernetes_rescheduling_tpu.telemetry import (
    MetricsRegistry,
    get_registry,
    set_registry,
)
from kubernetes_rescheduling_tpu.utils.logging import StructuredLogger
from kubernetes_rescheduling_tpu.utils.retry import RetryPolicy, call_with_retry


@pytest.fixture()
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


# ---- utils.retry ----


class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self, registry):
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("transient")
            return "ok"

        out = call_with_retry(
            flaky,
            policy=RetryPolicy(max_attempts=3, base_delay_s=1.0, jitter_frac=0.0),
            label="t",
            sleeper=sleeps.append,
        )
        assert out == "ok"
        assert sleeps == [1.0, 2.0]  # exponential backoff
        fam = registry.counter("boundary_retries_total", labelnames=("call",))
        assert fam.labels(call="t").value == 2

    def test_exhaustion_reraises_last_and_counts(self, registry):
        def dead():
            raise TimeoutError("still down")

        with pytest.raises(TimeoutError, match="still down"):
            call_with_retry(
                dead,
                policy=RetryPolicy(max_attempts=2, base_delay_s=0.0),
                label="t",
                sleeper=lambda s: None,
            )
        fam = registry.counter("boundary_failures_total", labelnames=("call",))
        assert fam.labels(call="t").value == 1

    def test_non_retryable_raises_immediately(self, registry):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise TypeError("programming error")

        with pytest.raises(TypeError):
            call_with_retry(
                broken,
                policy=RetryPolicy(max_attempts=5, base_delay_s=0.0),
                retryable=lambda e: isinstance(e, ConnectionError),
                sleeper=lambda s: None,
            )
        assert calls["n"] == 1  # no second attempt

    def test_deadline_stops_retrying(self, registry):
        sleeps = []

        def dead():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            call_with_retry(
                dead,
                policy=RetryPolicy(
                    max_attempts=10, base_delay_s=5.0, jitter_frac=0.0,
                    deadline_s=1.0,
                ),
                sleeper=sleeps.append,
            )
        assert sleeps == []  # the first backoff would already overrun

    def test_retry_none(self, registry):
        outs = iter([None, None, "late"])
        out = call_with_retry(
            lambda: next(outs),
            policy=RetryPolicy(
                max_attempts=3, base_delay_s=0.0, retry_none=True
            ),
            sleeper=lambda s: None,
        )
        assert out == "late"
        # all-None exhausts to None (not an exception)
        out = call_with_retry(
            lambda: None,
            policy=RetryPolicy(
                max_attempts=2, base_delay_s=0.0, retry_none=True
            ),
            sleeper=lambda s: None,
        )
        assert out is None

    def test_jitter_is_seeded_deterministic(self):
        p = RetryPolicy(base_delay_s=1.0, jitter_frac=0.5)
        a = p.backoff_s(2, random.Random(7))
        b = p.backoff_s(2, random.Random(7))
        assert a == b
        assert 1.0 <= a <= 3.0  # 2.0 ± 50%

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0).validate()
        with pytest.raises(ValueError):
            RetryPolicy(jitter_frac=1.5).validate()

    def test_is_transient_shared_predicate(self):
        from kubernetes_rescheduling_tpu.utils.retry import is_transient

        assert is_transient(ConnectionError("reset"))
        assert is_transient(TimeoutError("slow"))
        throttled = Exception("throttled")
        throttled.status = 503
        assert is_transient(throttled)
        definitive = Exception("gone")
        definitive.status = 404
        assert not is_transient(definitive)
        # definitive local answers fail fast, never burn the retry budget
        assert not is_transient(FileNotFoundError("no kubeconfig"))
        assert not is_transient(PermissionError("unreadable CA bundle"))
        assert not is_transient(TypeError("bug"))


# ---- circuit breaker ----


class TestCircuitBreaker:
    def make(self, **kw):
        kw.setdefault("max_consecutive_failures", 3)
        kw.setdefault("cooldown_rounds", 2)
        return CircuitBreaker(**kw)

    def test_opens_after_consecutive_failures(self, registry):
        br = self.make()
        br.on_round_start(1)
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open"
        assert br.transitions[-1]["to"] == "open"
        fam = registry.counter(
            "circuit_breaker_transitions_total", labelnames=("to",)
        )
        assert fam.labels(to="open").value == 1

    def test_success_resets_count(self, registry):
        br = self.make()
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"

    def test_half_open_probe_then_close_or_reopen(self, registry):
        br = self.make()
        br.on_round_start(1)
        for _ in range(3):
            br.record_failure()
        assert br.state == "open"
        assert br.on_round_start(2) == "open"  # cooldown not elapsed
        assert br.on_round_start(3) == "half_open"
        br.record_failure()  # failed probe → straight back to open
        assert br.state == "open"
        assert br.on_round_start(5) == "half_open"
        br.record_success()  # good probe → closed
        assert br.state == "closed"
        tos = [t["to"] for t in br.transitions]
        assert tos == ["open", "half_open", "open", "half_open", "closed"]

    def test_disabled_never_opens(self, registry):
        br = self.make(max_consecutive_failures=0)
        for _ in range(50):
            br.record_failure()
        assert br.state == "closed"


# ---- chaos backend ----


def _sim():
    b = make_backend("mubench", seed=1)
    b.inject_imbalance("worker1")
    return b


class TestChaosBackend:
    def test_profiles_validate(self):
        for name, prof in PROFILES.items():
            assert prof.validate().name == name
        with pytest.raises(ValueError):
            ChaosProfile(monitor_error_rate=1.5).validate()
        with pytest.raises(ValueError):
            ChaosProfile(monitor_corrupt_rate=-0.1).validate()
        with pytest.raises(ValueError):
            ChaosProfile(corrupt_max_pods=0).validate()
        with pytest.raises(ValueError):
            with_chaos(_sim(), "no-such-profile")
        # the soak profile exercises the reconcile-plane kinds at low
        # rates; the reconcile profile runs them hot
        assert PROFILES["soak"].monitor_corrupt_rate > 0
        assert PROFILES["soak"].external_drift_rate > 0
        assert PROFILES["reconcile"].move_lost_rate > 0

    def test_none_profile_is_passthrough(self):
        b = _sim()
        assert with_chaos(b, "none") is b

    def test_seeded_fault_stream_is_deterministic(self, registry):
        def run(seed):
            chaos = ChaosBackend(_sim(), PROFILES["soak"], seed=seed)
            for _ in range(40):
                try:
                    chaos.monitor()
                except ChaosError:
                    pass
            return dict(chaos.fault_counts)

        assert run(3) == run(3)
        assert run(3) != run(4)  # different seed, different stream

    def test_injected_registry_receives_fault_counters(self):
        """An explicitly injected registry gets the chaos counters — the
        fault_counts==registry invariant must not depend on the process
        default."""
        own = MetricsRegistry()
        chaos = ChaosBackend(
            _sim(), ChaosProfile(monitor_error_rate=1.0), seed=0, registry=own
        )
        with pytest.raises(ChaosError):
            chaos.monitor()
        fam = own.counter("chaos_faults_total", labelnames=("kind",))
        assert fam.labels(kind="monitor_error").value == 1

    def test_fault_counts_match_registry(self, registry):
        chaos = ChaosBackend(_sim(), PROFILES["soak"], seed=0)
        for _ in range(30):
            try:
                chaos.monitor()
            except ChaosError:
                pass
        assert chaos.fault_counts  # something was injected at these rates
        fam = registry.counter("chaos_faults_total", labelnames=("kind",))
        for kind, n in chaos.fault_counts.items():
            assert fam.labels(kind=kind).value == n

    def test_monitor_corrupt_poisons_readings_not_shapes(self, registry):
        from kubernetes_rescheduling_tpu.backends.chaos import ChaosBackend

        prof = ChaosProfile(monitor_corrupt_rate=1.0, corrupt_max_pods=3)
        chaos = ChaosBackend(_sim(), prof, seed=0)
        clean = chaos.inner.monitor()
        state = chaos.monitor()
        valid = np.asarray(state.pod_valid)
        bad = np.zeros_like(valid)
        # corruption spans BOTH Metrics-API usage fields (cpu and mem)
        for field, cap_field in (
            ("pod_cpu", "node_cpu_cap"),
            ("pod_mem", "node_mem_cap"),
        ):
            arr = np.asarray(getattr(state, field))
            cap = float(np.max(np.asarray(getattr(state, cap_field))))
            bad |= valid & (~np.isfinite(arr) | (arr < 0.0) | (arr > cap))
            assert arr.shape == np.asarray(getattr(clean, field)).shape
        assert 1 <= int(bad.sum()) <= 3  # 1..corrupt_max_pods entries
        assert chaos.fault_counts["monitor_corrupt"] == 1

    def test_pod_move_wave_gets_landing_faults(self, registry):
        """Regression: ``apply_pod_moves`` used to pass through
        ``__getattr__`` untouched, so pod-granular batch waves never saw
        lost/wrong-node faults — the reconcile profile's own soak never
        exercised the ledger on the pod path."""
        from kubernetes_rescheduling_tpu.backends.base import MoveRequest

        backend = _sim()
        chaos = ChaosBackend(backend, PROFILES["reconcile"], seed=5)
        state = backend.monitor()
        valid = np.flatnonzero(np.asarray(state.pod_valid))
        svcs = np.asarray(state.pod_service)
        graph = backend.comm_graph()
        moves = [
            MoveRequest(
                service=graph.names[int(svcs[i])],
                pod=state.pod_names[int(i)],
                target_node="worker2",
            )
            for i in valid[:6]
        ]
        for _ in range(12):
            landed = chaos.apply_pod_moves(moves)
            if chaos.fault_counts.get(
                "move_lost", 0
            ) and chaos.fault_counts.get("move_wrong_node", 0):
                break
        assert chaos.fault_counts.get("move_lost", 0) >= 1
        assert chaos.fault_counts.get("move_wrong_node", 0) >= 1
        # the wave reports TRUE landings (pod -> node): a wrong-node
        # redirect shows where the pod really went, and an acknowledged-
        # but-lost move claims the requested target while the cluster
        # kept the pod — only the reconcile diff can see that lie
        assert isinstance(landed, dict)
        fam = registry.counter("chaos_faults_total", labelnames=("kind",))
        for kind, n in chaos.fault_counts.items():
            assert fam.labels(kind=kind).value == n

    def test_external_drift_moves_a_pod_behind_the_controller(self, registry):
        from kubernetes_rescheduling_tpu.backends.chaos import ChaosBackend

        prof = ChaosProfile(external_drift_rate=1.0)
        sim = _sim()
        chaos = ChaosBackend(sim, prof, seed=0)
        before = sim.monitor()
        after = chaos.monitor()  # drift applies BEFORE the snapshot
        moved = (
            np.asarray(before.pod_node) != np.asarray(after.pod_node)
        ) & np.asarray(after.pod_valid)
        assert int(moved.sum()) == 1  # exactly one pod drifted
        assert chaos.fault_counts["external_drift"] == 1

    def test_move_lost_acknowledges_without_moving(self, registry):
        from kubernetes_rescheduling_tpu.backends.base import MoveRequest
        from kubernetes_rescheduling_tpu.backends.chaos import ChaosBackend

        prof = ChaosProfile(move_lost_rate=1.0)
        sim = _sim()
        chaos = ChaosBackend(sim, prof, seed=0)
        before = sim.monitor()
        landed = chaos.apply_move(
            MoveRequest(service="s0", target_node="worker2")
        )
        assert landed == "worker2"  # the API said yes...
        after = sim.monitor()
        assert np.array_equal(  # ...and nothing in the cluster changed
            np.asarray(before.pod_node), np.asarray(after.pod_node)
        )
        assert chaos.fault_counts["move_lost"] == 1

    def test_reconcile_profile_fault_counts_match_registry(self, registry):
        """The fault-count==registry acceptance invariant, extended to
        the reconcile-plane kinds (corrupt/drift/lost + wrong-node +
        node flap, all active in the `reconcile` profile)."""
        from kubernetes_rescheduling_tpu.backends.base import MoveRequest
        from kubernetes_rescheduling_tpu.backends.chaos import ChaosBackend

        chaos = ChaosBackend(_sim(), PROFILES["reconcile"], seed=0)
        for _ in range(30):
            chaos.monitor()
            chaos.apply_move(
                MoveRequest(service="s0", target_node="worker2")
            )
        for kind in ("monitor_corrupt", "external_drift", "move_lost"):
            assert chaos.fault_counts.get(kind, 0) >= 1, kind
        fam = registry.counter("chaos_faults_total", labelnames=("kind",))
        for kind, n in chaos.fault_counts.items():
            assert fam.labels(kind=kind).value == n

    def test_aux_stream_leaves_legacy_fault_sequence_unchanged(self, registry):
        """The reconcile-plane kinds draw from a DEDICATED seeded stream
        (ChaosBackend._rng_aux): enabling them must not shift the
        pre-existing kinds' seeded fault sequence — soaks pinned before
        the reconciliation plane existed keep their exact faults."""
        from kubernetes_rescheduling_tpu.backends.base import MoveRequest
        from kubernetes_rescheduling_tpu.backends.chaos import ChaosBackend

        legacy = dataclasses.replace(
            PROFILES["soak"],
            monitor_corrupt_rate=0.0,
            external_drift_rate=0.0,
            move_lost_rate=0.0,
        )

        def run(prof):
            chaos = ChaosBackend(_sim(), prof, seed=5)
            for _ in range(40):
                try:
                    chaos.monitor()
                except ChaosError:
                    pass
                try:
                    chaos.apply_move(
                        MoveRequest(service="s0", target_node="worker2")
                    )
                except (ChaosError, TimeoutError):
                    pass
            return chaos.fault_counts

        with_new, without = run(PROFILES["soak"]), run(legacy)
        new_kinds = {"monitor_corrupt", "external_drift", "move_lost"}
        for kind in (set(with_new) | set(without)) - new_kinds:
            assert with_new.get(kind, 0) == without.get(kind, 0), kind

    def test_stale_snapshot_is_previous_state(self, registry):
        prof = ChaosProfile(monitor_stale_rate=1.0)
        chaos = ChaosBackend(_sim(), prof, seed=0)
        first = chaos.monitor()  # nothing cached yet → real snapshot
        assert first is not None
        # mutate the cluster; a stale monitor must NOT see it
        chaos.inner.kill_node("worker1")
        again = chaos.monitor()
        assert again is first
        assert chaos.fault_counts["monitor_stale"] == 1

    def test_partial_snapshot_drops_pods_not_shapes(self, registry):
        prof = ChaosProfile(monitor_partial_rate=1.0, partial_drop_frac=0.3)
        chaos = ChaosBackend(_sim(), prof, seed=0)
        full = chaos.inner.monitor()
        part = chaos.monitor()
        assert part.pod_valid.shape == full.pod_valid.shape
        n_full = int(np.asarray(full.pod_valid).sum())
        n_part = int(np.asarray(part.pod_valid).sum())
        assert n_part == n_full - int(n_full * 0.3)

    def test_wrong_node_move_lands_elsewhere(self, registry):
        from kubernetes_rescheduling_tpu.backends.base import MoveRequest

        prof = ChaosProfile(move_wrong_node_rate=1.0)
        chaos = ChaosBackend(_sim(), prof, seed=0)
        landed = chaos.apply_move(
            MoveRequest(service="s0", target_node="worker2")
        )
        assert landed is not None and landed != "worker2"
        assert chaos.fault_counts["move_wrong_node"] == 1

    def test_move_timeout_consumes_inner_clock(self, registry):
        from kubernetes_rescheduling_tpu.backends.base import MoveRequest

        prof = ChaosProfile(move_timeout_rate=1.0, move_timeout_s=30.0)
        sim = _sim()
        chaos = ChaosBackend(sim, prof, seed=0)
        with pytest.raises(TimeoutError):
            chaos.apply_move(MoveRequest(service="s0", target_node="worker2"))
        assert sim.clock_s == 30.0

    def test_node_flap_kills_and_revives(self, registry):
        prof = ChaosProfile(node_flap_period=3, node_flap_down_calls=2)
        sim = _sim()
        chaos = ChaosBackend(sim, prof, seed=0)
        saw_dead = False
        for _ in range(10):
            state = chaos.monitor()
            if not bool(np.asarray(state.node_valid).all()):
                saw_dead = True
        assert saw_dead
        assert chaos.fault_counts["node_kill"] >= 1
        assert chaos.fault_counts["node_revive"] >= 1
        # the last revive schedule eventually restores every node
        assert chaos.fault_counts["node_kill"] - chaos.fault_counts[
            "node_revive"
        ] in (0, 1)


# ---- boundary client ----


class _FlakyBackend:
    """Backend stub: scripted monitor/apply_move outcomes."""

    def __init__(self, monitor_script=(), move_script=()):
        self.monitor_script = list(monitor_script)
        self.move_script = list(move_script)
        self.advanced = []

    def _pop(self, script, default):
        item = script.pop(0) if script else default
        if isinstance(item, BaseException):
            raise item
        return item

    def monitor(self):
        return self._pop(self.monitor_script, "state")

    def apply_move(self, move):
        return self._pop(self.move_script, "worker1")

    def comm_graph(self):
        return "graph"

    def advance(self, seconds):
        self.advanced.append(seconds)


class TestBoundaryClient:
    def make(self, backend, **kw):
        kw.setdefault("policy", RetryPolicy(max_attempts=2, base_delay_s=0.0))
        kw.setdefault(
            "breaker",
            CircuitBreaker(max_consecutive_failures=2, cooldown_rounds=1),
        )
        return BoundaryClient(backend, **kw)

    def test_retries_then_succeeds(self, registry):
        b = _FlakyBackend(monitor_script=[ConnectionError("x"), "fresh"])
        bd = self.make(b)
        assert bd.monitor() == "fresh"
        assert bd.breaker.consecutive_failures == 0
        assert b.advanced  # the backoff waited on the backend clock

    def test_exhausted_monitor_returns_none_and_counts(self, registry):
        b = _FlakyBackend(
            monitor_script=[ConnectionError("x"), ConnectionError("x")]
        )
        bd = self.make(b)
        bd.begin_round(1)
        assert bd.monitor() is None
        assert bd.round_failures == 1
        assert bd.breaker.consecutive_failures == 1

    def test_absorbs_status_bearing_api_errors(self, registry):
        """A kubernetes-client-shaped ApiException (has .status) with a
        throttling/server-side status is transient to the boundary; a
        definitive status (404) is not."""

        class ApiExc(Exception):
            def __init__(self, status):
                self.status = status

        b = _FlakyBackend(monitor_script=[ApiExc(503), ApiExc(503)])
        bd = self.make(b)
        bd.begin_round(1)
        assert bd.monitor() is None  # absorbed after retries, not raised
        assert bd.breaker.consecutive_failures == 1

        b2 = _FlakyBackend(monitor_script=[ApiExc(404)])
        with pytest.raises(ApiExc):
            self.make(b2).monitor()

    def test_startup_success_while_open_recloses_breaker(self, registry):
        """The startup probe loop can succeed while the breaker is OPEN
        (opened by the failed probes themselves); the success must close
        it — a healthy just-probed backend must not cost skipped rounds."""
        b = _FlakyBackend(
            monitor_script=[
                ConnectionError("x"), ConnectionError("x"),
                ConnectionError("x"), "fresh",
            ]
        )
        bd = BoundaryClient(
            b,
            policy=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker(
                max_consecutive_failures=3, cooldown_rounds=2
            ),
        )
        for _ in range(3):
            assert bd.monitor() is None
        assert bd.breaker.state == "open"
        assert bd.monitor() == "fresh"
        assert bd.breaker.state == "closed"

    def test_programming_errors_propagate(self, registry):
        b = _FlakyBackend(monitor_script=[TypeError("bug")])
        bd = self.make(b)
        with pytest.raises(TypeError):
            bd.monitor()
        # and a plain RuntimeError (e.g. a monkeypatched crash in a test)
        b2 = _FlakyBackend(monitor_script=[RuntimeError("crash")])
        with pytest.raises(RuntimeError):
            self.make(b2).monitor()

    def test_open_breaker_freezes_moves(self, registry):
        b = _FlakyBackend()
        bd = self.make(b)
        bd.breaker.record_failure()
        bd.breaker.record_failure()  # opens at 2
        assert bd.breaker.state == "open"
        assert bd.apply_move(object()) is None
        assert b.move_script == []  # inner backend never touched

    def test_failure_budget_freezes_round(self, registry):
        b = _FlakyBackend(
            move_script=[ConnectionError("x"), ConnectionError("x"), "w"]
        )
        bd = self.make(
            b,
            policy=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker(max_consecutive_failures=0),
            failure_budget_per_round=1,
        )
        bd.begin_round(1)
        assert bd.apply_move(object()) is None  # burned the budget
        assert bd.moves_frozen
        assert bd.apply_move(object()) is None  # frozen, inner untouched
        assert len(b.move_script) == 2
        bd.begin_round(2)  # budget resets per round
        assert not bd.moves_frozen


# ---- controller degraded mode (integration) ----


def test_controller_clean_run_unchanged(registry):
    """With no chaos and no failures the resilience layer is invisible:
    every round records, nothing skips, the breaker never moves."""
    backend = _sim()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=4, sleep_after_action_s=0.0,
        seed=1,
    )
    result = run_controller(backend, cfg)
    assert len(result.rounds) == 4
    assert result.skipped_rounds == 0
    assert result.breaker_transitions == []
    assert result.boundary_failures == 0
    assert all(r.breaker_state == "closed" for r in result.rounds)
    assert all(not r.degraded for r in result.rounds)


def test_controller_config_chaos_wraps_backend(registry):
    """config.chaos wires the wrapper inside run_controller: the loop
    completes under injected faults and the registry shows them."""
    backend = _sim()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=10, sleep_after_action_s=0.0,
        seed=1,
        chaos=ChaosConfig(profile="flaky-monitor", seed=1),
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0),
        max_consecutive_failures=3,
    )
    result = run_controller(backend, cfg)
    assert len(result.rounds) + result.skipped_rounds == 10
    recs = registry.snapshot()
    kinds = {
        r["labels"].get("kind")
        for r in recs
        if r["metric"] == "chaos_faults_total"
    }
    assert kinds  # faults were injected and counted


def test_round_events_carry_resilience_fields(registry):
    logger = StructuredLogger(name="t")
    backend = _sim()
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=2, sleep_after_action_s=0.0,
        seed=1,
    )
    run_controller(backend, cfg, logger=logger)
    rounds = [r for r in logger.records if r["event"] == "round"]
    assert len(rounds) == 2
    for r in rounds:
        assert r["breaker"] == "closed"
        assert r["degraded"] is False
        assert r["boundary_failures"] == 0


# ---- acceptance: the chaos soak ----


def test_chaos_soak_acceptance(registry):
    """ISSUE 2 acceptance: ≥30 rounds under the seeded soak profile
    (monitor failures + move timeouts + node flap). The controller never
    raises (reaching the asserts proves it), the breaker opens and
    re-closes at least once, no round is silently lost, and the injected
    fault counts equal the registry's fault counters."""
    logger = StructuredLogger(name="soak")
    report = run_chaos_soak(
        profile="soak",
        rounds=35,
        seed=1,
        chaos_seed=0,
        retry=RetryPolicy(max_attempts=1),
        max_consecutive_failures=3,
        breaker_cooldown_rounds=2,
        failure_budget_per_round=2,
        logger=logger,
    )
    # ≥ 30 simulated rounds, each one accounted: a record or a counted skip
    assert report["rounds"] == 35
    assert report["records"] + report["skipped_rounds"] == 35
    assert report["skipped_rounds"] >= 1
    # the breaker opened into safe mode and recovered
    assert report["breaker_opens"] >= 1
    assert report["breaker_closes"] >= 1
    # injected-fault counts == the registry's fault counters, per kind
    assert report["faults_injected"] > 0
    fam = registry.counter("chaos_faults_total", labelnames=("kind",))
    for kind, n in report["fault_counts"].items():
        assert fam.labels(kind=kind).value == n
    # skip accounting agrees between result, registry, and event log
    fam = registry.counter("rounds_skipped_total", labelnames=("algorithm",))
    assert fam.labels(algorithm="communication").value == report["skipped_rounds"]
    events = [r["event"] for r in logger.records]
    assert events.count("round_skipped") == report["skipped_rounds"]
    assert events.count("round") == report["records"]
    assert "breaker" in events


def test_harness_chaos_cell_completes_and_reports(tmp_path, registry):
    """A chaos soak cell in the experiment matrix: faults hit the LOOP
    (run_controller's wrapped view) while the harness's before/after
    measurements stay on the raw backend, and the run record carries the
    resilience accounting."""
    from kubernetes_rescheduling_tpu.bench.harness import (
        ExperimentConfig,
        run_experiment,
    )
    from kubernetes_rescheduling_tpu.bench.loadgen import LoadGenConfig

    cfg = ExperimentConfig(
        algorithms=("communication",),
        repeats=1,
        rounds=5,
        scenario="mubench",
        out_dir=str(tmp_path),
        seed=3,
        chaos_profile="flaky-moves",
        chaos_seed=0,
        max_consecutive_failures=3,
        load=LoadGenConfig(requests_per_phase=256, chunk=256),
    )
    summary = run_experiment(cfg)
    run = summary["runs"][0]
    assert "skipped_rounds" in run and "boundary_failures" in run
    # every round accounted for
    rounds_jsonl = list(tmp_path.glob("session_*/communication/run_1/rounds.jsonl"))
    assert len(rounds_jsonl) == 1
    recorded = len(rounds_jsonl[0].read_text().splitlines())
    assert recorded + run["skipped_rounds"] == 5


# ---- config plumbing ----


def test_config_toml_nested_resilience_blocks(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        "algorithm = 'communication'\n"
        "max_consecutive_failures = 7\n"
        "[retry]\n"
        "max_attempts = 4\n"
        "base_delay_s = 0.25\n"
        "[chaos]\n"
        "profile = 'flaky-moves'\n"
        "seed = 9\n"
    )
    cfg = RescheduleConfig.from_toml(p)
    assert cfg.retry == RetryPolicy(max_attempts=4, base_delay_s=0.25)
    assert cfg.chaos == ChaosConfig(profile="flaky-moves", seed=9)
    assert cfg.max_consecutive_failures == 7


def test_config_resilience_validation():
    with pytest.raises(ValueError):
        RescheduleConfig(max_consecutive_failures=-1).validate()
    with pytest.raises(ValueError):
        RescheduleConfig(breaker_cooldown_rounds=0).validate()
    with pytest.raises(ValueError):
        RescheduleConfig(retry=RetryPolicy(max_attempts=0)).validate()


# ---- satellite: k8s narrow exceptions + swallowed-error counter ----


class _ApiError(Exception):
    def __init__(self, status):
        self.status = status


class _MiniCore:
    def list_node(self, watch=False):
        return {
            "items": [
                {
                    "metadata": {"name": n},
                    "status": {"capacity": {"cpu": "8", "memory": "16Gi"}},
                }
                for n in ("master", "worker1")
            ]
        }

    def list_namespaced_pod(self, namespace, watch=False):
        return {"items": []}


class _RaisingCustom:
    def __init__(self, exc):
        self.exc = exc

    def list_cluster_custom_object(self, *a, **kw):
        raise self.exc

    def list_namespaced_custom_object(self, *a, **kw):
        raise self.exc


def _k8s_backend(custom_exc):
    from kubernetes_rescheduling_tpu.backends.k8s import K8sBackend
    from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c

    return K8sBackend(
        workmodel=mubench_workmodel_c(),
        core_api=_MiniCore(),
        apps_api=object(),
        custom_api=_RaisingCustom(custom_exc),
        sleeper=lambda s: None,
    )


def test_k8s_swallows_api_errors_with_log_and_counter(registry):
    backend = _k8s_backend(_ApiError(503))
    state = backend.monitor()  # metrics-server down: usage stays 0
    assert state.num_nodes == 1  # master excluded
    fam = registry.counter(
        "backend_swallowed_errors_total", labelnames=("backend", "call")
    )
    assert fam.labels(backend="k8s", call="monitor.node_metrics").value == 1
    assert fam.labels(backend="k8s", call="monitor.pod_metrics").value == 1
    # the structured log saw both swallows too
    swallowed = [
        r for r in backend.slog.records if r["event"] == "swallowed_error"
    ]
    assert len(swallowed) >= 2


def test_k8s_programming_errors_are_not_swallowed(registry):
    backend = _k8s_backend(TypeError("bug in the adapter"))
    with pytest.raises(TypeError, match="bug in the adapter"):
        backend.monitor()
    # interpreter-level RuntimeError subclasses are coding bugs, not API
    # weather — they must stay fatal too
    backend = _k8s_backend(RecursionError("runaway parse"))
    with pytest.raises(RecursionError):
        backend.monitor()


def test_k8s_create_conflict_after_delete_counts_as_success(registry):
    """409 AlreadyExists on the create-after-delete path = the first
    (response-lost) create attempt landed; the move must report success,
    mirroring the 404-on-delete rule."""
    from kubernetes_rescheduling_tpu.backends.base import MoveRequest
    from kubernetes_rescheduling_tpu.backends.k8s import K8sBackend
    from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c

    body = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "s0", "namespace": "default"},
        "spec": {
            "replicas": 1,
            "template": {"metadata": {}, "spec": {"containers": []}},
        },
    }

    class ConflictApps:
        def __init__(self):
            self.deleted = False

        def read_namespaced_deployment(self, name, namespace):
            if self.deleted:
                raise _ApiError(404)
            return body

        def delete_namespaced_deployment(self, name, namespace, body=None):
            self.deleted = True

        def create_namespaced_deployment(self, namespace, body):
            raise _ApiError(409)  # our retried create collided with itself

    backend = K8sBackend(
        workmodel=mubench_workmodel_c(),
        core_api=_MiniCore(),
        apps_api=ConflictApps(),
        custom_api=_RaisingCustom(_ApiError(404)),
        sleeper=lambda s: None,
        delete_timeout_s=0.01,
        delete_poll_interval_s=0.001,
    )
    landed = backend.apply_move(
        MoveRequest(service="s0", target_node="worker1", mechanism="nodeName")
    )
    assert landed == "worker1"
    # and it was NOT counted as a swallowed error
    fam = registry.counter(
        "backend_swallowed_errors_total", labelnames=("backend", "call")
    )
    assert fam.labels(
        backend="k8s", call="apply_move.create_deployment"
    ).value == 0


def test_k8s_retries_throttled_status(registry):
    """429/5xx retry under the adapter's policy; a definitive 404 does not."""
    calls = {"n": 0}

    class FlakyCustom(_RaisingCustom):
        def list_cluster_custom_object(self, *a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise _ApiError(503)
            return {"items": []}

        def list_namespaced_custom_object(self, *a, **kw):
            return {"items": []}

    backend = _k8s_backend(_ApiError(404))
    backend.custom_api = FlakyCustom(None)
    backend.monitor()
    assert calls["n"] == 2  # the 503 was retried, then succeeded
    fam = registry.counter("boundary_retries_total", labelnames=("call",))
    assert fam.labels(call="k8s.node_metrics").value == 1


# ---- satellite: crash-safe checkpoints + mid-round crash resume ----


def test_checkpoint_save_atomically_replaces_torn_predecessor(tmp_path):
    from kubernetes_rescheduling_tpu.core.topology import mubench_scenario
    from kubernetes_rescheduling_tpu.utils.checkpoint import CheckpointManager

    scn = mubench_scenario()
    # a previous crash left a torn (garbage) checkpoint for round 5
    (tmp_path / "round_000005.npz").write_bytes(b"not a zip")
    (tmp_path / "round_000005.json").write_text("{broken")
    mgr = CheckpointManager(tmp_path)
    mgr.save(5, scn.state, extra={"cost": 1.0})  # os.replace overwrites both
    r, state, extra = mgr.latest()
    assert r == 5 and extra["cost"] == 1.0
    np.testing.assert_array_equal(
        np.asarray(state.pod_node), np.asarray(scn.state.pod_node)
    )


def test_resume_replays_crashed_round_with_identical_decisions(tmp_path):
    """Kill the loop inside on_round (a raising sink) mid-round 3, resume
    from checkpoint_dir on a fresh backend: the crashed round replays and
    every fold_in-derived decision matches the uninterrupted run."""
    import jax

    rounds = 6
    cfg = RescheduleConfig(
        algorithm="communication", max_rounds=rounds,
        sleep_after_action_s=0.0, seed=5,
    )

    def fields(rec):
        return (rec.round, rec.moved, rec.services_moved, rec.target,
                rec.most_hazard)

    clean = run_controller(
        _sim(), cfg, key=jax.random.PRNGKey(5),
        checkpoint_dir=str(tmp_path / "clean"),
    )

    class Crash(RuntimeError):
        pass

    def crashing_sink(rec, state):
        if rec.round == 3:
            raise Crash("sink died")

    ckpt = str(tmp_path / "crashy")
    with pytest.raises(Crash):
        run_controller(
            _sim(), cfg, key=jax.random.PRNGKey(5),
            checkpoint_dir=ckpt, on_round=crashing_sink,
        )

    resumed = run_controller(
        _sim(), cfg, key=jax.random.PRNGKey(5), checkpoint_dir=ckpt
    )
    # checkpoints exist for rounds 1-2 only → round 3 is REPLAYED
    assert resumed.resumed_from_round == 3
    assert [r.round for r in resumed.rounds] == list(range(3, rounds + 1))
    expected = [fields(r) for r in clean.rounds[2:]]
    assert [fields(r) for r in resumed.rounds] == expected


# ---- report surfacing ----


def test_report_summarizes_resilience_events():
    from kubernetes_rescheduling_tpu.telemetry.report import summarize_events

    records = [
        {"event": "round", "round": 1, "moved": True, "degraded": False,
         "communication_cost": 5.0, "decision_latency_s": 0.01},
        {"event": "boundary_failure", "call": "monitor", "error": "x"},
        {"event": "breaker", "round": 2, "from": "closed", "to": "open"},
        {"event": "round_skipped", "round": 2, "breaker": "open"},
        {"event": "breaker", "round": 4, "from": "open", "to": "half_open"},
        {"event": "breaker", "round": 4, "from": "half_open", "to": "closed"},
        {"event": "round", "round": 4, "moved": False, "degraded": True,
         "communication_cost": 4.0, "decision_latency_s": 0.01},
    ]
    text = "\n".join(summarize_events(records))
    assert "breaker: closed->open@r2" in text
    assert "skipped=1" in text
    assert "degraded=1" in text
    assert "boundary_failures=1" in text
