"""Logging, profiling, checkpoint/resume."""

import json

import numpy as np
import pytest

from kubernetes_rescheduling_tpu.core.topology import mubench_scenario
from kubernetes_rescheduling_tpu.utils import (
    CheckpointManager,
    LatencyHistogram,
    StructuredLogger,
    Timer,
    load_state,
    save_state,
    trace_to,
)


def test_structured_logger(tmp_path):
    log = StructuredLogger(name="t", path=tmp_path / "log.jsonl", level="info")
    log.debug("hidden")          # below level
    log.info("round", n=1, cost=3.5)
    log.error("boom", reason="x")
    lines = [json.loads(l) for l in (tmp_path / "log.jsonl").read_text().splitlines()]
    assert [l["event"] for l in lines] == ["round", "boom"]
    assert lines[0]["cost"] == 3.5
    assert len(log.records) == 2


def test_timer_and_histogram():
    with Timer() as t:
        sum(range(1000))
    assert t.elapsed_s > 0
    h = LatencyHistogram()
    assert h.summary() == {"count": 0}
    for v in [0.01, 0.02, 0.03]:
        h.add(v)
    s = h.summary()
    assert s["count"] == 3
    assert s["mean_ms"] == pytest.approx(20.0)
    assert s["decisions_per_sec"] == pytest.approx(50.0)


def test_trace_to_noop():
    with trace_to(None):
        pass  # must not require jax.profiler


def test_trace_to_is_the_spans_object():
    """The deprecation shim resolves to the ONE implementation in
    telemetry.spans — both import paths are the same object, so a fix
    lands in both and the duplicate can never drift back."""
    from kubernetes_rescheduling_tpu.telemetry import spans
    from kubernetes_rescheduling_tpu.utils import profiling

    assert profiling.trace_to is spans.trace_to
    assert trace_to is spans.trace_to  # the utils package re-export too


def test_state_roundtrip(tmp_path):
    scn = mubench_scenario()
    save_state(scn.state, tmp_path / "ckpt", extra={"round": 3})
    state, extra = load_state(tmp_path / "ckpt")
    assert extra["round"] == 3
    np.testing.assert_array_equal(
        np.asarray(state.pod_node), np.asarray(scn.state.pod_node)
    )
    assert state.node_names == scn.state.node_names
    # derived metrics still work on the restored state
    assert float(state.node_cpu_pct().sum()) >= 0


def test_checkpoint_manager_resume_and_gc(tmp_path):
    scn = mubench_scenario()
    mgr = CheckpointManager(tmp_path, keep=3)
    assert mgr.latest() is None
    for r in range(1, 8):
        mgr.save(r, scn.state, extra={"cost": float(r)})
    r, state, extra = mgr.latest()
    assert r == 7
    assert extra["cost"] == 7.0
    assert len(list(tmp_path.glob("round_*.npz"))) == 3  # gc kept last 3
