"""Property-based tests (hypothesis) — the invariants SURVEY §4 commits to:
capacity constraints never violated, policy choices never land on hazard
nodes, quantity parsing is total and monotone, admission is safe for any
input. Randomized far wider than the seeded fixtures elsewhere."""

import jax
import jax.numpy as jnp
import pytest
import numpy as np

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this environment"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from kubernetes_rescheduling_tpu.core.quantities import (
    cpu_to_millicores,
    format_millicores,
    mem_to_bytes,
)
from kubernetes_rescheduling_tpu.core.state import ClusterState
from kubernetes_rescheduling_tpu.core.workmodel import mubench_workmodel_c
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.ops import (
    fused_score_admission,
    reference_score_admission,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS, choose_node, detect_hazard
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

SETTINGS = settings(max_examples=25, deadline=None)


# ---- quantities -----------------------------------------------------------

_CPU_SUFFIX = st.sampled_from(["", "m", "n", "u"])


@SETTINGS
@given(st.integers(min_value=0, max_value=10**9), _CPU_SUFFIX)
def test_cpu_parse_total_and_nonnegative(value, suffix):
    out = cpu_to_millicores(f"{value}{suffix}")
    assert isinstance(out, int) and out >= 0


@SETTINGS
@given(st.integers(min_value=0, max_value=10**6))
def test_cpu_parse_monotone_in_value(value):
    # more cores can never parse to fewer millicores
    assert cpu_to_millicores(str(value + 1)) >= cpu_to_millicores(str(value))
    assert cpu_to_millicores(f"{value + 1}m") >= cpu_to_millicores(f"{value}m")


@SETTINGS
@given(st.integers(min_value=0, max_value=10**7))
def test_millicores_format_parse_roundtrip(m):
    assert cpu_to_millicores(format_millicores(m)) == m


_MEM_MULT = {"": 1, "Ki": 2**10, "Mi": 2**20, "Gi": 2**30,
             "k": 10**3, "M": 10**6, "G": 10**9}


@SETTINGS
@given(
    st.integers(min_value=0, max_value=10**6),
    st.sampled_from(sorted(_MEM_MULT)),
)
def test_mem_parse_scales_exactly(value, suffix):
    # the k8s quantity grammar: binary Ki/Mi/Gi, decimal lowercase-k/M/G
    out = mem_to_bytes(f"{value}{suffix}")
    assert out == value * _MEM_MULT[suffix]


# ---- policies -------------------------------------------------------------

def _state_from(pod_nodes, pod_cpu, n_nodes, cap):
    n_pods = len(pod_nodes)
    return ClusterState.build(
        node_names=[f"w{i:02d}" for i in range(n_nodes)],
        node_cpu_cap=[cap] * n_nodes,
        node_mem_cap=[1e9] * n_nodes,
        pod_services=list(range(n_pods)),
        pod_nodes=pod_nodes,
        pod_cpu=pod_cpu,
        pod_mem=[0.0] * n_pods,
        pod_names=[f"s{i}-0" for i in range(n_pods)],
    )


@SETTINGS
@given(
    st.integers(min_value=0, max_value=2**31 - 1),
    st.sampled_from(list(POLICY_IDS)),
)
def test_choice_never_lands_on_hazard_node(seed, policy):
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(2, 8))
    n_pods = int(rng.integers(1, 20))
    state = _state_from(
        rng.integers(0, n_nodes, n_pods).tolist(),
        (rng.integers(1, 10, n_pods) * 100.0).tolist(),
        n_nodes,
        cap=4000.0,
    )
    graph = mubench_workmodel_c().comm_graph()
    _, mask = detect_hazard(state, threshold=30.0)
    got = int(
        choose_node(
            jnp.asarray(POLICY_IDS[policy]),
            state,
            graph,
            jnp.asarray(int(rng.integers(0, min(n_pods, 20)))),
            mask,
            jax.random.PRNGKey(seed % 1000),
        )
    )
    mask = np.asarray(mask)
    if mask.all():
        assert got == -1          # nowhere to go -> explicit no-choice
    else:
        assert got >= 0
        assert not mask[got]      # anti-affinity always respected


# ---- admission safety -----------------------------------------------------

@SETTINGS
@given(st.integers(min_value=0, max_value=2**31 - 1))
def test_admission_never_overcommits(seed):
    """For ANY instance: per target node, pre-chunk load plus all admitted
    arrivals stays within capacity (departures deliberately not credited)."""
    rng = np.random.default_rng(seed)
    C = int(rng.integers(2, 48))
    N = int(rng.integers(2, 32))
    M = jnp.asarray(rng.integers(0, 5, (C, N)).astype(np.float32))
    cur = jnp.asarray(rng.integers(0, N, C), jnp.int32)
    c_cpu = jnp.asarray(rng.integers(1, 6, C) * 100.0, jnp.float32)
    c_mem = jnp.zeros((C,), jnp.float32)
    valid = jnp.asarray(rng.random(C) < 0.95)
    cap_val = float(rng.integers(5, 20) * 100)
    cap = jnp.full((N,), cap_val, jnp.float32)
    load = jnp.asarray(rng.uniform(0, cap_val, N), jnp.float32)
    common = (M, cur, c_cpu, c_mem, valid, load, jnp.zeros((N,)), cap,
              jnp.full((N,), jnp.inf), jnp.ones((N,), bool))
    ref = reference_score_admission(*common, 0.3, None, enforce_capacity=True)
    fused = fused_score_admission(
        *common, 0.3, 0.0, seed,
        enforce_capacity=True, use_noise=False, interpret=True, block_c=16,
    )
    for new_node, admitted in (ref, fused[:2]):
        new_node, admitted = np.asarray(new_node), np.asarray(admitted)
        arrivals = np.zeros(N)
        moved = np.where(admitted, np.asarray(c_cpu), 0.0)
        mask = admitted & (new_node != np.asarray(cur))
        np.add.at(arrivals, new_node[mask], moved[mask])
        assert (np.asarray(load) + arrivals <= np.asarray(cap) + 1e-3).all()


# ---- solver ---------------------------------------------------------------

@SETTINGS
@given(st.integers(min_value=0, max_value=2**31 - 1))
def test_solver_never_worse_and_capacity_safe(seed):
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(2, 6))
    n_pods = 20
    cap = 4000.0
    state = _state_from(
        rng.integers(0, n_nodes, n_pods).tolist(),
        (rng.integers(1, 8, n_pods) * 100.0).tolist(),
        n_nodes,
        cap=cap,
    )
    graph = mubench_workmodel_c().comm_graph()
    lam = 0.5
    cfg = GlobalSolverConfig(sweeps=3, balance_weight=lam, enforce_capacity=True)

    def combined(st):
        # the solver's FULL objective: comm + λ·std + overload repulsion,
        # via the solver's OWN balance-terms helper (one definition —
        # hand-rolling it here would silently diverge under capacity_frac
        # or a future objective edit). Omitting the overload term makes
        # the invariant falsifiable — the solver may correctly trade
        # comm/std for draining an over-budget node (hypothesis found
        # seed 33631 doing exactly that).
        from kubernetes_rescheduling_tpu.solver.global_solver import (
            pct_balance_terms,
        )

        budget_cap = np.asarray(st.node_cpu_cap)[:n_nodes] * cfg.capacity_frac
        return float(communication_cost(st, graph)) + float(
            pct_balance_terms(
                np.asarray(st.node_cpu_used())[:n_nodes],
                budget_cap,
                np.ones(n_nodes, bool),
                lam,
                cfg.overload_weight,
                xp=np,
            )
        )

    before = combined(state)
    new_state, info = global_assign(
        state, graph, jax.random.PRNGKey(seed % 997), cfg
    )
    # never worse on the solver's combined objective (its guarantee)
    assert combined(new_state) <= before + 1e-3
    # capacity respected wherever the input respected it
    used0 = np.asarray(state.node_cpu_used())[:n_nodes]
    used1 = np.asarray(new_state.node_cpu_used())[:n_nodes]
    ok0 = used0 <= cap
    assert (used1[ok0] <= cap + 1e-3).all()


@settings(max_examples=30, deadline=None)
@given(
    s=st.integers(min_value=2, max_value=24),
    n=st.integers(min_value=2, max_value=8),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
)
def test_exact_comm_cost_matches_bruteforce(s, n, seed):
    """The shared exact cut-sum (the never-worse gate's comm term) equals a
    double-loop reference on arbitrary weighted graphs."""
    from kubernetes_rescheduling_tpu.solver.global_solver import exact_comm_cost

    rng = np.random.default_rng(seed)
    adj = rng.random((s, s)).astype(np.float32) * (rng.random((s, s)) < 0.5)
    adj = (adj + adj.T) / 2
    np.fill_diagonal(adj, 0.0)
    rv = rng.integers(0, 4, s).astype(np.float32)
    assign = rng.integers(0, n, s)
    got = float(exact_comm_cost(jnp.asarray(adj), jnp.asarray(rv), jnp.asarray(assign)))
    want = 0.5 * sum(
        float(adj[i, j]) * float(rv[i]) * float(rv[j])
        for i in range(s)
        for j in range(s)
        if assign[i] != assign[j]
    )
    assert got == pytest.approx(want, rel=1e-4, abs=1e-4)


@settings(max_examples=30, deadline=None)
@given(
    c=st.sampled_from([2, 4, 8, 256]),
    n_chunks=st.integers(min_value=1, max_value=6),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
)
def test_sweep_composition_is_partition(c, n_chunks, seed):
    """Every composition (B=1 full permutation AND B=256 block-granular —
    the latter only engages when a caller requests it, i.e. the
    inline-mass path) partitions [0, SP) exactly once per sweep."""
    from kubernetes_rescheduling_tpu.solver.global_solver import sweep_composition

    sp = c * n_chunks
    for block in (1, 256):
        ids, _ = sweep_composition(
            jax.random.PRNGKey(seed), sp, c, n_chunks, block=block
        )
        assert ids.shape == (n_chunks, c)
        flat = np.asarray(ids).reshape(-1)
        assert sorted(flat.tolist()) == list(range(sp))


@settings(max_examples=20, deadline=None)
@given(
    k=st.integers(min_value=1, max_value=6),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
)
def test_top_gain_moves_invariants(k, seed):
    """Wave cap invariants: result ⊆ changed, ≤ k entries, only
    strictly-improving moves, original relative order preserved."""
    from kubernetes_rescheduling_tpu.bench.controller import _top_gain_moves
    from kubernetes_rescheduling_tpu.core.state import CommGraph
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    rng = np.random.default_rng(seed)
    s, n = 8, 3
    names = [f"s{i}" for i in range(s)]
    rel = {names[i]: [names[j] for j in range(s) if i != j and rng.random() < 0.4]
           for i in range(s)}
    graph = CommGraph.from_relation(rel, names=names)
    state = ClusterState.build(
        node_names=[f"n{i}" for i in range(n)],
        node_cpu_cap=[1000.0] * n,
        node_mem_cap=[2**30] * n,
        pod_services=list(range(s)),
        pod_nodes=rng.integers(0, n, s).tolist(),
        pod_cpu=(rng.random(s) * 100).tolist(),
        pod_mem=[0.0] * s,
        pod_names=[f"{nm}-0" for nm in names],
    )
    changed = [
        (i, int(rng.integers(0, n))) for i in rng.permutation(s)[: rng.integers(1, s)]
    ]
    cfg = GlobalSolverConfig(balance_weight=0.5, enforce_capacity=False)
    out = _top_gain_moves(changed, state, graph, cfg, k)
    assert len(out) <= k
    assert all(m in changed for m in out)
    idxs = [changed.index(m) for m in out]
    assert idxs == sorted(idxs)  # stable original order


@SETTINGS
@given(
    s=st.integers(min_value=2, max_value=120),
    e=st.integers(min_value=0, max_value=300),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
    weighted=st.booleans(),
)
def test_sparse_graph_round_trip_and_cut_parity(s, e, seed, weighted):
    """For ARBITRARY edge lists (dupes accumulated, self-loops dropped):
    the block-local storage round-trips to the exact dense adjacency, and
    the COO cut cost equals the dense exact cut for random assignments
    and replica counts."""
    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.sparsegraph import (
        sparse_pair_comm_cost,
    )
    from kubernetes_rescheduling_tpu.solver.global_solver import exact_comm_cost

    rng = np.random.default_rng(seed)
    src = rng.integers(0, s, size=e)
    dst = rng.integers(0, s, size=e)
    w = (
        rng.integers(1, 6, size=e).astype(np.float64)
        if weighted
        else np.ones(e)
    )
    sg = sparsegraph.from_edges(src, dst, w, s, bu=128, reg_tiles=1)
    # dense reconstruction: symmetrized, accumulated, zero diagonal
    expect = np.zeros((s, s))
    for a, b, ww in zip(src, dst, w):
        if a != b:
            expect[a, b] += ww
            expect[b, a] += ww
    got = np.asarray(sg.to_dense().adj)
    np.testing.assert_allclose(got, expect, rtol=1e-6)

    assign = jnp.asarray(rng.integers(0, 5, size=s), jnp.int32)
    rv = jnp.asarray(rng.integers(1, 4, size=s), jnp.float32)
    dense_cut = float(exact_comm_cost(jnp.asarray(expect, jnp.float32), rv, assign))
    perm = jnp.clip(sg.perm, 0, s - 1)
    sparse_cut = float(
        sparse_pair_comm_cost(sg, assign[perm], rv[perm] * (sg.perm < s))
    )
    assert sparse_cut == pytest.approx(dense_cut, rel=1e-5, abs=1e-5)
    # block partition invariants: every real service appears in exactly
    # one block slot; hub/regular blocks partition the block ids
    p = np.asarray(sg.perm)
    assert sorted(p[p < s].tolist()) == list(range(s))
    assert sorted(sg.hub_blocks + sg.regular_blocks) == list(
        range(sg.num_blocks)
    )
