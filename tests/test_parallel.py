"""Mesh-sharded solver paths on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.parallel import (
    make_mesh,
    parallel_restarts,
    sharded_choose_node,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS, choose_node, detect_hazard
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig


def test_make_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    m = make_mesh(8)
    assert m.shape == {"dp": 8, "tp": 1}
    m2 = make_mesh(8, shape=(4, 2))
    assert m2.shape == {"dp": 4, "tp": 2}
    m1 = make_mesh(1)
    assert m1.shape == {"dp": 1, "tp": 1}


def test_parallel_restarts_beats_or_matches_single():
    scn = synthetic_scenario(n_pods=64, n_nodes=8, seed=4, mean_degree=5.0)
    mesh = make_mesh(8)
    cfg = GlobalSolverConfig(sweeps=4)
    best_state, info = parallel_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(0), mesh, config=cfg
    )
    objs = np.asarray(info["restart_objectives"])
    assert objs.shape == (8,)
    assert float(info["objective_after"]) == pytest.approx(objs.min())
    # selected state really achieves the reported objective
    assert float(communication_cost(best_state, scn.graph)) <= objs.min() + 1e-3
    before = float(communication_cost(scn.state, scn.graph))
    assert float(info["objective_after"]) <= before


@pytest.mark.parametrize("policy", ["spread", "binpack", "kubescheduling", "communication"])
def test_sharded_choose_node_matches_unsharded(policy):
    scn = synthetic_scenario(n_pods=64, n_nodes=8, seed=2, mean_degree=5.0)
    mesh = make_mesh(8, shape=(2, 4))
    _, hazard_mask = detect_hazard(scn.state, threshold=30.0)
    if bool(hazard_mask.all()):
        pytest.skip("all nodes hazardous")
    pid = jnp.asarray(POLICY_IDS[policy])
    svc = jnp.asarray(3)
    key = jax.random.PRNGKey(0)
    expected = int(choose_node(pid, scn.state, scn.graph, svc, hazard_mask, key))
    got = int(
        sharded_choose_node(pid, scn.state, scn.graph, svc, hazard_mask, key, mesh)
    )
    assert got == expected
