"""Mesh-sharded solver paths on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.parallel import (
    make_mesh,
    parallel_restarts,
    sharded_choose_node,
    solve_with_restarts,
)
from kubernetes_rescheduling_tpu.policies import POLICY_IDS, choose_node, detect_hazard
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign


def test_make_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    m = make_mesh(8)
    assert m.shape == {"dp": 8, "tp": 1}
    m2 = make_mesh(8, shape=(4, 2))
    assert m2.shape == {"dp": 4, "tp": 2}
    m1 = make_mesh(1)
    assert m1.shape == {"dp": 1, "tp": 1}


def test_parallel_restarts_beats_or_matches_single():
    scn = synthetic_scenario(n_pods=64, n_nodes=8, seed=4, mean_degree=5.0)
    mesh = make_mesh(8)
    cfg = GlobalSolverConfig(sweeps=4)
    best_state, info = parallel_restarts(
        scn.state, scn.graph, jax.random.PRNGKey(0), mesh, config=cfg
    )
    objs = np.asarray(info["restart_objectives"])
    assert objs.shape == (8,)
    assert float(info["objective_after"]) == pytest.approx(objs.min())
    # selected state really achieves the reported objective
    assert float(communication_cost(best_state, scn.graph)) <= objs.min() + 1e-3
    before = float(communication_cost(scn.state, scn.graph))
    assert float(info["objective_after"]) <= before


def test_solve_with_restarts_single_matches_global_assign():
    """n_restarts=1 degenerates to the plain solver (same keys, same result)."""
    scn = synthetic_scenario(n_pods=64, n_nodes=8, seed=5, mean_degree=5.0)
    cfg = GlobalSolverConfig(sweeps=4)
    key = jax.random.PRNGKey(3)
    st1, info1 = solve_with_restarts(scn.state, scn.graph, key, n_restarts=1, config=cfg)
    st2, info2 = global_assign(scn.state, scn.graph, key, cfg)
    assert int(info1["restarts"]) == 1
    np.testing.assert_array_equal(np.asarray(st1.pod_node), np.asarray(st2.pod_node))


@pytest.mark.slow  # best-of-N >= single stays pinned fast by
# test_parallel_restarts_beats_or_matches_single below
def test_solve_with_restarts_multi_beats_or_matches_single_powerlaw():
    """The VERDICT-r1 wiring requirement: best-of-N on the mesh is never
    worse than a single solve on the power-law scenario."""
    scn = synthetic_scenario(
        n_pods=256, n_nodes=16, seed=6, powerlaw=True, mean_degree=4.0
    )
    cfg = GlobalSolverConfig(sweeps=4)
    key = jax.random.PRNGKey(0)
    _, single_info = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=1, config=cfg
    )
    multi_state, multi_info = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=8, config=cfg
    )
    assert int(multi_info["restarts"]) == 8
    assert float(multi_info["objective_after"]) <= float(
        single_info["objective_after"]
    ) + 1e-3
    # reported objective is achieved by the returned placement
    assert float(communication_cost(multi_state, scn.graph)) == pytest.approx(
        float(multi_info["objective_after"]), abs=1e-2
    )


def test_solve_with_restarts_auto_mesh_odd_count():
    """Restart counts that don't divide the device count still run (largest
    divisor <= devices). n_restarts=3 -> dp=3 mesh, one restart per shard."""
    scn = synthetic_scenario(n_pods=32, n_nodes=8, seed=7, mean_degree=4.0)
    _, info = solve_with_restarts(
        scn.state,
        scn.graph,
        jax.random.PRNGKey(1),
        n_restarts=3,
        config=GlobalSolverConfig(sweeps=2),
    )
    assert int(info["restarts"]) == 3
    assert info["restart_objectives"].shape == (3,)


def test_solve_with_restarts_single_device_sequential():
    """The dp=1 degradation path: several restarts scanned back to back on
    one device (prime count > device count forces dp=1)."""
    scn = synthetic_scenario(n_pods=32, n_nodes=8, seed=8, mean_degree=4.0)
    mesh = make_mesh(1)
    _, info = solve_with_restarts(
        scn.state,
        scn.graph,
        jax.random.PRNGKey(2),
        n_restarts=5,
        config=GlobalSolverConfig(sweeps=2),
        mesh=mesh,
    )
    assert int(info["restarts"]) == 5
    assert info["restart_objectives"].shape == (5,)
    before = float(communication_cost(scn.state, scn.graph))
    assert float(info["objective_after"]) <= before


@pytest.mark.slow  # tp-sharded == single-device stays pinned fast by
# test_sharded_solve_with_restarts_matches_dp_only and the
# capacity+noise sharded case below
def test_sharded_global_assign_matches_single_device():
    """The node-sharded SPMD solver (tp=4) makes the same decisions as the
    single-device solver with annealing off — the collectives (all_gather
    argmax, psum'd score/slack contributions) are exact reformulations."""
    from kubernetes_rescheduling_tpu.parallel import sharded_global_assign

    scn = synthetic_scenario(n_pods=200, n_nodes=16, seed=11, mean_degree=5.0)
    mesh = make_mesh(8, shape=(2, 4))
    cfg = GlobalSolverConfig(sweeps=3, noise_temp=0.0, balance_weight=0.5)
    key = jax.random.PRNGKey(5)
    st_sh, info_sh = sharded_global_assign(scn.state, scn.graph, key, mesh, cfg)
    st_1, info_1 = global_assign(scn.state, scn.graph, key, cfg)
    np.testing.assert_array_equal(
        np.asarray(st_sh.pod_node), np.asarray(st_1.pod_node)
    )
    assert float(info_sh["objective_after"]) == pytest.approx(
        float(info_1["objective_after"])
    )
    before = float(communication_cost(scn.state, scn.graph))
    assert float(communication_cost(st_sh, scn.graph)) <= before


def test_sharded_global_assign_with_capacity_and_noise():
    """Budget + repulsion + annealing all run under the sharded solver;
    never-worse holds on its own objective."""
    from kubernetes_rescheduling_tpu.parallel import sharded_global_assign

    scn = synthetic_scenario(n_pods=128, n_nodes=8, seed=12, mean_degree=4.0)
    mesh = make_mesh(8, shape=(1, 8))
    cfg = GlobalSolverConfig(
        sweeps=3, balance_weight=0.5, enforce_capacity=True, capacity_frac=0.5
    )
    st, info = sharded_global_assign(
        scn.state, scn.graph, jax.random.PRNGKey(0), mesh, cfg
    )
    assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-3
    assert int(info["tp"]) == 8


def test_sharded_global_assign_rejects_indivisible_nodes():
    from kubernetes_rescheduling_tpu.parallel import sharded_global_assign

    scn = synthetic_scenario(n_pods=32, n_nodes=6, seed=1, mean_degree=4.0)
    mesh = make_mesh(8, shape=(2, 4))  # 6 % 4 != 0
    with pytest.raises(ValueError, match="must be a multiple"):
        sharded_global_assign(
            scn.state, scn.graph, jax.random.PRNGKey(0), mesh, GlobalSolverConfig()
        )


def test_sharded_solve_with_restarts_matches_dp_only():
    """dp restarts OF tp-sharded solves: with annealing noise off, the
    composed (2, 4) mesh path picks the same placement as the dp-only
    best-of-N (which itself equals per-restart single-device solves) —
    the key mapping and the first-minimum selection order agree."""
    from kubernetes_rescheduling_tpu.parallel import sharded_solve_with_restarts

    scn = synthetic_scenario(n_pods=200, n_nodes=16, seed=13, mean_degree=5.0)
    cfg = GlobalSolverConfig(sweeps=3, noise_temp=0.0, balance_weight=0.5)
    key = jax.random.PRNGKey(7)
    st_c, info_c = sharded_solve_with_restarts(
        scn.state, scn.graph, key, make_mesh(8, shape=(2, 4)),
        n_restarts=2, config=cfg,
    )
    st_d, info_d = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=2, config=cfg,
        mesh=make_mesh(2, shape=(2, 1)),
    )
    np.testing.assert_array_equal(
        np.asarray(st_c.pod_node), np.asarray(st_d.pod_node)
    )
    np.testing.assert_allclose(
        np.asarray(info_c["restart_objectives"]),
        np.asarray(info_d["restart_objectives"]),
        rtol=1e-5,
    )
    assert int(info_c["best_restart"]) == int(info_d["best_restart"])


def test_solve_with_restarts_tp_composed_never_worse():
    """The production entry point with --tp: auto-shapes a (dp, tp) mesh
    and best-of-4 is never worse than a single tp-sharded solve."""
    scn = synthetic_scenario(n_pods=128, n_nodes=8, seed=14, mean_degree=4.0)
    cfg = GlobalSolverConfig(sweeps=3)
    key = jax.random.PRNGKey(0)
    _, single = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=1, config=cfg, tp=2
    )
    st, multi = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=4, config=cfg, tp=2
    )
    assert int(multi["restarts"]) == 4
    assert int(multi["tp"]) == 2
    assert multi["restart_objectives"].shape == (4,)
    assert float(multi["objective_after"]) <= float(single["objective_after"]) + 1e-3
    before = float(communication_cost(scn.state, scn.graph))
    assert float(multi["objective_after"]) <= before + 1e-3


def test_controller_global_routes_through_tp_solver(monkeypatch):
    """solver_tp wiring end to end: the control loop's global round reaches
    the SPMD node-sharded composed solver — a production path, not demo
    code reachable only from tests/dryrun."""
    import kubernetes_rescheduling_tpu.parallel.sharded_solver as ss
    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.config import RescheduleConfig

    calls = {"n": 0}
    real = ss.sharded_solve_with_restarts

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ss, "sharded_solve_with_restarts", counting)
    backend = make_backend("dense", seed=0)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm="global",
        max_rounds=1,
        sleep_after_action_s=0.0,
        solver_restarts=2,
        solver_tp=2,
        balance_weight=0.5,
        seed=0,
    )
    res = run_controller(backend, cfg, key=jax.random.PRNGKey(0))
    assert len(res.rounds) == 1
    assert calls["n"] == 1


@pytest.mark.parametrize("policy", ["spread", "binpack", "kubescheduling", "communication"])
def test_sharded_choose_node_matches_unsharded(policy):
    scn = synthetic_scenario(n_pods=64, n_nodes=8, seed=2, mean_degree=5.0)
    mesh = make_mesh(8, shape=(2, 4))
    _, hazard_mask = detect_hazard(scn.state, threshold=30.0)
    if bool(hazard_mask.all()):
        pytest.skip("all nodes hazardous")
    pid = jnp.asarray(POLICY_IDS[policy])
    svc = jnp.asarray(3)
    key = jax.random.PRNGKey(0)
    expected = int(choose_node(pid, scn.state, scn.graph, svc, hazard_mask, key))
    got = int(
        sharded_choose_node(pid, scn.state, scn.graph, svc, hazard_mask, key, mesh)
    )
    assert got == expected


@pytest.mark.slow  # move-cost parity across lowerings stays pinned
# fast by test_sharded_sparse.test_move_cost_parity_and_gate
def test_sharded_move_cost_parity_with_single_chip():
    """Disruption pricing composes with tp: the node-sharded dense solver
    makes the same decisions as global_assign under move_cost (noise off,
    balance 0 — integer arithmetic), and its gate covers the restart bill."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.parallel import make_mesh, sharded_global_assign
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    scn = synthetic_scenario(
        n_pods=256, n_nodes=16, powerlaw=True, seed=11, mean_degree=4.0
    )
    cfg = GlobalSolverConfig(
        sweeps=3, noise_temp=0.0, balance_weight=0.0, move_cost=1.0
    )
    key = jax.random.PRNGKey(3)
    st_single, info_s = global_assign(scn.state, scn.graph, key, cfg)
    mesh = make_mesh(8, shape=(2, 4))
    st_shard, info_h = sharded_global_assign(scn.state, scn.graph, key, mesh, cfg)
    np.testing.assert_array_equal(
        np.asarray(st_single.pod_node), np.asarray(st_shard.pod_node)
    )
    assert float(info_s["move_penalty"]) == float(info_h["move_penalty"])
    # a priced-out solve (huge cost) stays put through the sharded path too
    pricey = GlobalSolverConfig(
        sweeps=3, noise_temp=0.0, balance_weight=0.0, move_cost=1e9
    )
    st_frozen, info_f = sharded_global_assign(
        scn.state, scn.graph, key, mesh, pricey
    )
    np.testing.assert_array_equal(
        np.asarray(st_frozen.pod_node), np.asarray(scn.state.pod_node)
    )


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="dp vs dp×tp selection parity was validated on the stable "
    "jax.shard_map API; the jax.experimental fallback (parallel.compat, "
    "pre-0.6 jax) diverges on this case's collective reduction order",
)
def test_restart_selection_parity_under_move_cost():
    """Best-of-N selection ranks the gated penalized value on BOTH restart
    paths: dp-only (tp=1) and dp×tp pick the same final placement under
    disruption pricing (noise off — per-restart decisions are bit-equal,
    so any divergence would be the selection rule)."""
    from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
    from kubernetes_rescheduling_tpu.parallel import solve_with_restarts
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    scn = synthetic_scenario(
        n_pods=256, n_nodes=16, powerlaw=True, seed=13, mean_degree=4.0
    )
    cfg = GlobalSolverConfig(
        sweeps=3, noise_temp=0.0, balance_weight=0.0, move_cost=1.0
    )
    key = jax.random.PRNGKey(9)
    st_dp, info_dp = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=2, config=cfg, tp=1
    )
    st_tp, info_tp = solve_with_restarts(
        scn.state, scn.graph, key, n_restarts=2, config=cfg, tp=4
    )
    np.testing.assert_array_equal(
        np.asarray(st_dp.pod_node), np.asarray(st_tp.pod_node)
    )
