"""Quantity-parsing semantics (reference unit_convertion.py:1-39)."""

import pytest

from kubernetes_rescheduling_tpu.core.quantities import (
    cpu_to_millicores,
    format_bytes_as_mi,
    format_millicores,
    mem_to_bytes,
)


class TestCpu:
    def test_millicores_pass_through(self):
        assert cpu_to_millicores("53m") == 53

    def test_millicores_truncate(self):
        # reference unit_convertion.py:5 uses int(float(...)) — truncation
        assert cpu_to_millicores("53.9m") == 53

    def test_nanocores(self):
        assert cpu_to_millicores("1000000n") == 1
        assert cpu_to_millicores("1500000n") == 2  # rounds

    def test_microcores(self):
        assert cpu_to_millicores("1500u") == 2

    def test_bare_cores(self):
        assert cpu_to_millicores("2") == 2000
        assert cpu_to_millicores("0.5") == 500
        assert cpu_to_millicores(4) == 4000

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            cpu_to_millicores("")


class TestMem:
    @pytest.mark.parametrize(
        "q,expected",
        [
            ("1Ki", 1024),
            ("536Mi", 536 * 1024**2),
            ("2Gi", 2 * 1024**3),
            ("1Ti", 1024**4),
            ("1Pi", 1024**5),
            ("1Ei", 1024**6),
        ],
    )
    def test_binary_suffixes(self, q, expected):
        assert mem_to_bytes(q) == expected

    def test_bare_bytes(self):
        assert mem_to_bytes("12345678") == 12345678

    def test_decimal_suffixes(self):
        assert mem_to_bytes("1k") == 1000
        assert mem_to_bytes("5M") == 5_000_000
        assert mem_to_bytes("2G") == 2_000_000_000

    def test_exponent_notation(self):
        assert mem_to_bytes("1e6") == 1_000_000

    def test_fractional_binary(self):
        assert mem_to_bytes("1.5Ki") == 1536


class TestFormat:
    def test_millicores(self):
        assert format_millicores(1234) == "1234m"

    def test_bytes_as_mi(self):
        assert format_bytes_as_mi(536 * 1024**2) == "536Mi"
        assert format_bytes_as_mi(1024**2 + 524288) == "2Mi"  # rounds


class TestMetricsServerQuirks:
    """metrics-server can emit sub-byte memory quantities (e.g. '3988799488m'
    millibytes); these must parse instead of crashing the live adapter."""

    def test_millibytes(self):
        assert mem_to_bytes("3988799488m") == 3988799
        assert mem_to_bytes("100m") == 0

    def test_microbytes(self):
        assert mem_to_bytes("5000000u") == 5

    def test_nanobytes(self):
        assert mem_to_bytes("2000000000n") == 2
