"""Per-replica placement mode: expansion correctness, the splitting win
over whole-deployment placement, and never-worse at scale."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign
from kubernetes_rescheduling_tpu.solver.pod_mode import (
    global_assign_pods,
    pod_level_graph,
)
from kubernetes_rescheduling_tpu.solver.sparse_solver import sparse_pod_comm_cost


def test_pod_graph_expansion_matches_pod_level_metric():
    """The expanded graph's cut equals the dense pod-level comm metric for
    arbitrary placements (each pod pair counted once at the service
    weight)."""
    scn = synthetic_scenario(
        n_pods=120, n_nodes=6, powerlaw=True, seed=4, replicas=3
    )
    pg = pod_level_graph(scn.state, scn.graph)
    view = scn.state.replace(
        pod_service=jnp.arange(scn.state.num_pods, dtype=jnp.int32)
    )
    rng = np.random.default_rng(0)
    for _ in range(3):
        nodes = jnp.asarray(
            rng.integers(0, 6, size=scn.state.num_pods), jnp.int32
        )
        st = scn.state.replace(pod_node=nodes)
        vw = view.replace(pod_node=nodes)
        dense_metric = float(communication_cost(st, scn.graph))
        sparse_metric = float(sparse_pod_comm_cost(vw, pg))
        assert dense_metric == pytest.approx(sparse_metric, rel=1e-6)


@pytest.mark.slow  # the splits-replicas capability stays pinned fast by test_capacity_stuck_fixture_through_controller below: the SAME stuck fixture driven end-to-end through the controller, asserting the final placement realizes the split service mode cannot reach — this is the kernel-level redundant variant (own solver compile)
def test_pod_mode_splits_replicas_where_service_mode_cannot_move():
    """4 replicas of A on n1, their peer B on n0, caps that fit at most
    two 100m pods per node: whole-deployment placement is stuck (A cannot
    fit anywhere as a unit, B cannot join A), but per-replica placement
    moves one A pod next to B and cuts the cost."""
    graph = CommGraph.from_relation({"A": ["B"], "B": ["A"]}, names=["A", "B"])
    state = ClusterState.build(
        node_names=["n0", "n1", "n2", "n3"],
        node_cpu_cap=[250.0] * 4,
        node_mem_cap=[2**30] * 4,
        pod_services=[0, 0, 0, 0, 1],
        pod_nodes=[1, 1, 2, 2, 0],
        pod_cpu=[100.0] * 5,
        pod_mem=[0.0] * 5,
        pod_names=["A-0", "A-1", "A-2", "A-3", "B-0"],
    )
    cost0 = float(communication_cost(state, graph))
    assert cost0 == 4.0  # every A pod cross-node from B
    cfg = GlobalSolverConfig(sweeps=8, balance_weight=0.0)
    svc_state, _ = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
    svc_cost = float(communication_cost(svc_state, graph))
    pod_state, info = global_assign_pods(
        state, graph, jax.random.PRNGKey(0), cfg
    )
    pod_cost = float(communication_cost(pod_state, graph))
    # service mode cannot place the 400m Deployment anywhere; B's node has
    # no room for 4 more pods — it is stuck at 4.0
    assert svc_cost == 4.0
    # pod mode colocates one replica with B within the budget
    assert pod_cost < svc_cost
    # and capacity still holds
    loads = np.zeros(4)
    for i in range(5):
        loads[int(pod_state.pod_node[i])] += 100.0
    assert (loads <= 250.0).all()


@pytest.mark.slow  # pod-mode never-worse stays pinned fast by the
# splits-replicas and capacity-stuck controller cases
def test_pod_mode_never_worse_at_scale():
    scn = synthetic_scenario(
        n_pods=1024, n_nodes=16, powerlaw=True, seed=7, replicas=2,
        node_cpu_cap_m=8_000.0,
    )
    before = float(communication_cost(scn.state, scn.graph))
    pod_state, info = global_assign_pods(
        scn.state, scn.graph, jax.random.PRNGKey(1),
        GlobalSolverConfig(sweeps=4),
    )
    after = float(communication_cost(pod_state, scn.graph))
    assert after <= before
    assert after < before  # improvement available on this instance
    assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-4


def test_pod_graph_from_sparse_matches_dense():
    """The sparse-direct expansion (COO in, no dense adjacency anywhere)
    must produce the same pod graph as the dense-input expansion."""
    from kubernetes_rescheduling_tpu.core import sparsegraph

    scn = synthetic_scenario(
        n_pods=300, n_nodes=6, powerlaw=True, seed=11, replicas=3
    )
    pg_dense = pod_level_graph(scn.state, scn.graph)
    sg = sparsegraph.from_comm_graph(scn.graph)
    pg_sparse = pod_level_graph(scn.state, sg)
    np.testing.assert_array_equal(
        np.asarray(pg_dense.u_ids), np.asarray(pg_sparse.u_ids)
    )
    np.testing.assert_allclose(
        np.asarray(pg_dense.w_local), np.asarray(pg_sparse.w_local)
    )
    np.testing.assert_array_equal(
        np.asarray(pg_dense.perm), np.asarray(pg_sparse.perm)
    )


@pytest.mark.slow  # dp/tp mesh composition stays pinned fast by
# test_parallel's dp/tp cases; pod-graph routing by the other
# pod-mode tests
def test_pod_mode_with_restarts_and_tp():
    """Per-replica placement is a production path: restarts and tp route
    through solve_with_restarts on the pod graph."""
    scn = synthetic_scenario(
        n_pods=512, n_nodes=8, powerlaw=True, seed=6, replicas=2,
        node_cpu_cap_m=8_000.0,
    )
    before = float(communication_cost(scn.state, scn.graph))
    cfg = GlobalSolverConfig(sweeps=3)
    st_r, info_r = global_assign_pods(
        scn.state, scn.graph, jax.random.PRNGKey(2), cfg, n_restarts=2
    )
    assert int(info_r["restarts"]) == 2
    assert float(communication_cost(st_r, scn.graph)) <= before
    st_t, info_t = global_assign_pods(
        scn.state, scn.graph, jax.random.PRNGKey(2), cfg, tp=4
    )
    assert int(info_t["tp"]) == 4
    assert float(communication_cost(st_t, scn.graph)) <= before


def test_capacity_stuck_fixture_through_controller():
    """The whole-Deployment-stuck fixture, driven through the CONTROLLER
    (placement_unit='pod'): per-pod MoveRequests land on the sim backend
    and the final cluster placement realizes the split that service mode
    cannot reach."""
    from kubernetes_rescheduling_tpu.backends.sim import SimBackend
    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.config import RescheduleConfig
    from kubernetes_rescheduling_tpu.core.workmodel import ServiceSpec, Workmodel

    wm = Workmodel(
        services=(
            ServiceSpec(name="A", callees=("B",), replicas=4,
                        cpu_request_millicores=100),
            ServiceSpec(name="B", replicas=1, cpu_request_millicores=100),
        ),
        source="test",
    )
    backend = SimBackend(
        workmodel=wm,
        node_names=["n0", "n1", "n2", "n3"],
        node_cpu_cap_m=250.0,
        seed=0,
    )
    # pin the stuck placement: all A pods away from B, no node can take
    # the whole 400m Deployment under a 250m budget
    for pod in backend._pods:
        pod[1] = {"A-0": 1, "A-1": 1, "A-2": 2, "A-3": 2, "B-0": 0}[pod[2]]
    graph = backend.comm_graph()
    state0 = backend.monitor()
    assert float(communication_cost(state0, graph)) == 4.0

    cfg = RescheduleConfig(
        algorithm="global",
        placement_unit="pod",
        max_rounds=3,
        enforce_capacity=True,
        capacity_frac=1.0,
        balance_weight=0.0,
        sleep_after_action_s=0.0,
    )
    result = run_controller(backend, cfg, key=jax.random.PRNGKey(0))
    final = backend.monitor()
    assert float(communication_cost(final, graph)) < 4.0
    # budgets hold on the realized cluster, not just the solver's plan
    assert np.all(np.asarray(final.node_cpu_used()) <= 250.0 + 1e-6)
    assert result.moves >= 1


def test_pod_mode_config_validation():
    from kubernetes_rescheduling_tpu.config import RescheduleConfig

    with pytest.raises(ValueError, match="algorithm='global'"):
        RescheduleConfig(
            algorithm="communication", placement_unit="pod"
        ).validate()
    with pytest.raises(ValueError, match="global_moves_cap"):
        RescheduleConfig(
            algorithm="global", placement_unit="pod", global_moves_cap=2
        ).validate()
