"""Per-replica placement mode: expansion correctness, the splitting win
over whole-deployment placement, and never-worse at scale."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_rescheduling_tpu.core.state import ClusterState, CommGraph
from kubernetes_rescheduling_tpu.core.topology import synthetic_scenario
from kubernetes_rescheduling_tpu.objectives import communication_cost
from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign
from kubernetes_rescheduling_tpu.solver.pod_mode import (
    global_assign_pods,
    pod_level_graph,
)
from kubernetes_rescheduling_tpu.solver.sparse_solver import sparse_pod_comm_cost


def test_pod_graph_expansion_matches_pod_level_metric():
    """The expanded graph's cut equals the dense pod-level comm metric for
    arbitrary placements (each pod pair counted once at the service
    weight)."""
    scn = synthetic_scenario(
        n_pods=120, n_nodes=6, powerlaw=True, seed=4, replicas=3
    )
    pg = pod_level_graph(scn.state, scn.graph)
    view = scn.state.replace(
        pod_service=jnp.arange(scn.state.num_pods, dtype=jnp.int32)
    )
    rng = np.random.default_rng(0)
    for _ in range(3):
        nodes = jnp.asarray(
            rng.integers(0, 6, size=scn.state.num_pods), jnp.int32
        )
        st = scn.state.replace(pod_node=nodes)
        vw = view.replace(pod_node=nodes)
        dense_metric = float(communication_cost(st, scn.graph))
        sparse_metric = float(sparse_pod_comm_cost(vw, pg))
        assert dense_metric == pytest.approx(sparse_metric, rel=1e-6)


def test_pod_mode_splits_replicas_where_service_mode_cannot_move():
    """4 replicas of A on n1, their peer B on n0, caps that fit at most
    two 100m pods per node: whole-deployment placement is stuck (A cannot
    fit anywhere as a unit, B cannot join A), but per-replica placement
    moves one A pod next to B and cuts the cost."""
    graph = CommGraph.from_relation({"A": ["B"], "B": ["A"]}, names=["A", "B"])
    state = ClusterState.build(
        node_names=["n0", "n1", "n2", "n3"],
        node_cpu_cap=[250.0] * 4,
        node_mem_cap=[2**30] * 4,
        pod_services=[0, 0, 0, 0, 1],
        pod_nodes=[1, 1, 2, 2, 0],
        pod_cpu=[100.0] * 5,
        pod_mem=[0.0] * 5,
        pod_names=["A-0", "A-1", "A-2", "A-3", "B-0"],
    )
    cost0 = float(communication_cost(state, graph))
    assert cost0 == 4.0  # every A pod cross-node from B
    cfg = GlobalSolverConfig(sweeps=8, balance_weight=0.0)
    svc_state, _ = global_assign(state, graph, jax.random.PRNGKey(0), cfg)
    svc_cost = float(communication_cost(svc_state, graph))
    pod_state, info = global_assign_pods(
        state, graph, jax.random.PRNGKey(0), cfg
    )
    pod_cost = float(communication_cost(pod_state, graph))
    # service mode cannot place the 400m Deployment anywhere; B's node has
    # no room for 4 more pods — it is stuck at 4.0
    assert svc_cost == 4.0
    # pod mode colocates one replica with B within the budget
    assert pod_cost < svc_cost
    # and capacity still holds
    loads = np.zeros(4)
    for i in range(5):
        loads[int(pod_state.pod_node[i])] += 100.0
    assert (loads <= 250.0).all()


def test_pod_mode_never_worse_at_scale():
    scn = synthetic_scenario(
        n_pods=1024, n_nodes=16, powerlaw=True, seed=7, replicas=2,
        node_cpu_cap_m=8_000.0,
    )
    before = float(communication_cost(scn.state, scn.graph))
    pod_state, info = global_assign_pods(
        scn.state, scn.graph, jax.random.PRNGKey(1),
        GlobalSolverConfig(sweeps=4),
    )
    after = float(communication_cost(pod_state, scn.graph))
    assert after <= before
    assert after < before  # improvement available on this instance
    assert float(info["objective_after"]) <= float(info["objective_before"]) + 1e-4
