#!/usr/bin/env python3
"""Headline benchmark — one JSON line for the driver.

Metric: device-side latency of one globally-optimal rescheduling round at
the north-star scale (10k pods / 1k nodes, power-law service mesh) on a
single chip — the batched global solve that replaces the reference's
one-deployment-per-round greedy loop (which is paced at 15 s/round,
reference main.py:27,100, and scores O(pods·nodes) in Python,
rescheduling.py:188-195).

The HEADLINE value is the device slope between K=2 and K=12 chained
rounds (prepared pair weights where the controller can reuse them) — the
stable reading that cancels dispatch + tunnel RTT. The pipelined and
fenced wall-clock readings (tunnel-noisy on this rig: ±10 ms measured)
live in ``extra`` with an explicit RTT attribution.

Baseline: BASELINE.md's target of <100 ms/round at 10k×1k. ``vs_baseline``
is baseline/value, so >1 means faster than target.

Environment knobs:
  BENCH_SCENARIO  large (default) | powerlaw | dense | mubench |
                  sparse50k (50k services × 2k nodes, sparse solver —
                  a scale the dense form cannot allocate) |
                  sparse100k (100k × 4k — dense would need ~56 GB) |
                  trace (streaming weight drift at 10k×1k, all steps
                  inside one compiled scan — BASELINE config 5 on chip;
                  honors BENCH_SOLVER) |
                  trace50k (the stream at 50k×2k — sparse-only: the
                  dense [S, S] scatter cannot allocate there) |
                  fleet (multi-tenant: BENCH_TENANTS same-shaped
                  BENCH_FLEET_SERVICES-svc × BENCH_FLEET_NODES-node
                  tenants decided by ONE vmap-batched dispatch vs N
                  sequential solo dispatches — emits the amortized
                  per-tenant ms and the vs_solo ratio for BOTH the
                  greedy kernel and the batched global solve
                  (fleet v2); the 1k-tenant fleet matrix is
                  BENCH_TENANTS=1024 BENCH_FLEET_SERVICES=2000
                  BENCH_FLEET_NODES=256) |
                  elastic (sustained churn: BENCH_ROUNDS controller
                  rounds of the powerlaw scenario under the seeded
                  diurnal-autoscale profile — replicas ×0.5–×2 with
                  traffic, one node drain/add cycle — emitting the
                  median device ms/round with the decision kernel's
                  trace count pinned at 1 + counted bucket promotions) |
                  pipeline (async pipelined control loop: the live greedy
                  loop at 10k×1k run sequential vs software-pipelined —
                  single-bundle round-end transfers, background monitor —
                  emitting the pipelined wall-clock ms/round with the
                  decisions pinned bit-identical, the RTT attribution,
                  and the overlap ratio; ledger series wall_round_ms) |
                  scan (device-resident round scan: the live greedy loop
                  at powerlaw 2k×200 run sequential vs pipelined vs
                  scanned — BENCH_SCAN_BLOCK rounds fused per lax.scan
                  dispatch, one round_end transfer per block — emitting
                  scanned rounds/sec (better: higher) with both
                  speedups, records pinned bit-identical, and the
                  scan kernel's trace count pinned at 1; CPU acceptance
                  is ≥5× vs pipelined, the 10× target is on-rig) |
                  forecast (predictive scheduling: BENCH_ROUNDS proactive
                  rounds of the powerlaw scenario under diurnal-autoscale
                  churn — the online per-node ridge forecaster + the
                  CAR-against-the-predicted-state decision kernel —
                  emitting the median device ms/round with forecast_skill
                  vs the persistence baseline and both kernels'
                  trace counts pinned at 1 + promotions) |
                  multichip (the measured multichip cell: fleet scan
                  blocks sharded over the dp mesh — one dispatch
                  advances every tenant BENCH_SCAN_BLOCK rounds with
                  each device scanning its own tenant block — emitting
                  fleet_scan_rounds_per_sec (better: higher) with the
                  per-device step rollup nested as its own ledger
                  series multichip_device_step_ms_p99 (better: lower)
                  and writing the measured MULTICHIP_rNN.json record;
                  forces BENCH_DEVICES virtual host-CPU devices on a
                  dev box, a no-op on a slice with real chips) |
                  serve (the serving plane: BENCH_SERVE_REQUESTS open-loop
                  arrivals at BENCH_SERVE_RPS through the bounded batcher
                  — the repo's first request-grain perf pair, emitting
                  placements/sec (better: higher) with the p99 request
                  latency nested as its own ledger series
                  serving_p99_ms (better: lower), exact shed/timeout
                  accounting, and the vmapped serve kernel's steady-state
                  trace count pinned at 1)
  BENCH_TENANTS   fleet/multichip scenarios: tenant count (default 16)
  BENCH_FLEET_SERVICES / BENCH_FLEET_NODES
                  fleet/multichip scenarios: per-tenant cluster shape
                  (defaults 2000 / 256 — the fleet-matrix cell shape)
  BENCH_DEVICES   multichip scenario only: dp mesh size to force on a
                  host without enough real devices (default 8; no-op
                  when real devices suffice)
  BENCH_MULTICHIP_OUT
                  multichip scenario only: path for the measured
                  MULTICHIP record (default: next free repo-root
                  MULTICHIP_rNN.json, NN >= 06)
  BENCH_ROUNDS    elastic/forecast scenarios: soak rounds (default 30);
                  scan scenario: timed rounds (default 48)
  BENCH_SCAN_BLOCK scan scenario: rounds fused per scan dispatch
                  (default 16); multichip scenario: rounds per sharded
                  scan block (default 8)
  BENCH_SERVE_REQUESTS / BENCH_SERVE_RPS / BENCH_SERVE_BATCH
                  serve scenario only: soak size (default 256), open-loop
                  arrival rate (default 200 req/s), batcher max_batch
                  (default 8)
  BENCH_SOLVER    dense (default) | sparse — solver for the scenario
  BENCH_SWEEPS    solver sweeps per round (default 9)
  BENCH_REPS      timed repetitions (default 5)
  BENCH_RESTARTS  best-of-N solves over the device mesh (default 1)
  BENCH_TRACE_DIR write a jax.profiler trace of the timed loop here
  BENCH_LEDGER    append the headline reading to this perf-ledger JSONL
                  (telemetry.perf_ledger schema; `telemetry perf` trends it)

Integer knobs are parsed with a clear error naming the variable — a typo'd
``BENCH_RESTARTS=two`` exits with the offending name/value instead of a
bare ValueError traceback.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

# the multichip scenario needs its dp devices provisioned BEFORE jax
# initializes a backend (XLA parses the host-device-count flag once per
# process) — so the env hook must sit above the jax import. Purely
# additive: on a slice whose real device count already covers
# BENCH_DEVICES the forced CPU count is never selected.
if os.environ.get("BENCH_SCENARIO") == "multichip":
    _n_dev = int(os.environ.get("BENCH_DEVICES", "8") or 8)
    _xla_flags = [
        _f
        for _f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in _f
    ]
    _xla_flags.append(f"--xla_force_host_platform_device_count={_n_dev}")
    os.environ["XLA_FLAGS"] = " ".join(_xla_flags)

import jax
import jax.numpy as jnp


def _env_int(name: str, default: int) -> int:
    """Integer env knob with a diagnosable failure mode: the error names
    the VARIABLE and the value it rejected."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw)
    except ValueError:
        raise SystemExit(
            f"bench: {name} must be an integer, got {raw!r}"
        ) from None


def _ledger_append(result: dict) -> None:
    """BENCH_LEDGER: append the headline reading to a perf ledger so
    `telemetry perf` can trend driver rounds without re-ingesting the
    raw snapshots."""
    path = os.environ.get("BENCH_LEDGER")
    if not path:
        return
    from kubernetes_rescheduling_tpu.telemetry.perf_ledger import PerfLedger

    extra = result.get("extra", {})
    devices = extra.get("devices") or []
    PerfLedger(path).append(
        metric=result["metric"],
        value=result["value"],
        unit=result.get("unit", "ms"),
        scenario=str(extra.get("scenario", "bench")),
        # multichip cells stamp an explicit platform×count identity
        # ("cpux8" vs "tpux8") so forced-host and real-slice runs can
        # never share a trend series; other cells key by first device
        device_kind=str(
            extra.get("device_kind")
            or (devices[0] if devices else "unknown")
        ),
        digest="bench-history",
        # latency cells trend down, throughput cells (the scan
        # scenario's rounds/sec) trend up — the record says which
        better=result.get("better", "lower"),
        vs_baseline=result.get("vs_baseline"),
    )


def _write_multichip_record(result: dict) -> None:
    """BENCH_SCENARIO=multichip: persist the measured MULTICHIP record.

    The r01–r05 records were dryrun receipts (``{ok, rc, n_devices}`` —
    "the dp plane dispatched somewhere"); from r06 the record is the
    MEASURED shape ``scripts/check_bench_schema.py`` validates: the
    ``fleet_scan_rounds_per_sec`` reading with its nested per-device
    rollup, keyed by an explicit ``device_kind`` so a forced-host CPU
    record can never be read as slice perf. ``BENCH_MULTICHIP_OUT``
    overrides the path; by default the next free repo-root
    ``MULTICHIP_rNN.json`` (NN >= 06) is taken."""
    root = os.path.dirname(os.path.abspath(__file__))
    out = os.environ.get("BENCH_MULTICHIP_OUT")
    if not out:
        n = 6
        while os.path.exists(os.path.join(root, f"MULTICHIP_r{n:02d}.json")):
            n += 1
        out = os.path.join(root, f"MULTICHIP_r{n:02d}.json")
    extra = result.get("extra", {})
    record = {
        "n_devices": int(extra.get("n_devices", 0)),
        "device_kind": str(extra.get("device_kind", "unknown")),
        "rc": 0,
        "ok": True,
        "measured": True,
        "cmd": "BENCH_SCENARIO=multichip python bench.py",
        "tail": json.dumps(result),
        "parsed": result,
    }
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")


def measure_rtt_ms(reps: int = 7) -> float:
    """Host↔device round-trip floor: dispatch a trivial compiled op and
    read one scalar back. On the tunneled rig this is ~100+ ms and
    dominates any single fenced solve; recording it makes the fenced
    reading's attribution explicit (fenced ≈ rtt + device + dispatch)."""

    @jax.jit
    def tick(x):
        return x + 1.0

    float(tick(jnp.float32(0)))  # compile
    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        float(tick(jnp.float32(i)))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


def slope_device_ms(chained, state, graph, k1=2, k2=12):
    """Pure device compute per round: K chained rounds inside ONE jitted
    program (true state dependency), fenced once; the slope between two
    K values cancels dispatch + tunnel RTT. Min-of-3 — contention only
    ever adds time."""

    def timed(k):
        _, objs = chained(state, graph, jax.random.PRNGKey(7), k)
        float(objs[-1])  # warm-up/compile
        best = float("inf")
        for rep in range(3):
            t = time.perf_counter()
            _, objs = chained(state, graph, jax.random.PRNGKey(8 + rep), k)
            float(objs[-1])  # completion fence
            best = min(best, time.perf_counter() - t)
        return best

    return (timed(k2) - timed(k1)) / (k2 - k1) * 1e3


def bench_trace(
    sweeps: int, baseline_ms: float, scenario: str, solver_kind: str
) -> dict:
    """BASELINE config 5 at flagship scale: per-step cost of tracking
    drifting traffic weights with the compiled-once solver, all steps on
    device. ``trace`` runs the 10k×1k mesh with the dense or sparse
    solver (BENCH_SOLVER); ``trace50k`` runs 50k×2k — only the sparse
    form's static-structure/dynamic-weights layout can express a stream
    at that scale (the dense [S, S] scatter cannot even allocate)."""
    from kubernetes_rescheduling_tpu.bench.trace import (
        drift_multipliers,
        drift_multipliers_sparse,
        replay_on_device,
        replay_on_device_sparse,
    )
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig

    cfg = GlobalSolverConfig(sweeps=sweeps)
    if scenario == "trace50k":
        solver_kind = "sparse"
        state, graph = _sparse50k_problem()
        sgraph = graph
    else:
        from kubernetes_rescheduling_tpu.bench.harness import make_backend

        backend = make_backend("large", seed=0)
        state = backend.monitor()
        graph = backend.comm_graph()
        if solver_kind == "sparse":
            from kubernetes_rescheduling_tpu.core import sparsegraph

            sgraph = sparsegraph.from_comm_graph(graph)

    ii, jj, loc = None, None, None
    mults_by_k = {}

    def timed(k):
        nonlocal ii, jj, loc, sgraph
        if k not in mults_by_k:
            if solver_kind == "sparse":
                sgraph, loc, mults_by_k[k] = drift_multipliers_sparse(
                    sgraph, k, seed=3
                )
            else:
                ii, jj, mults_by_k[k] = drift_multipliers(graph, k, seed=3)
        m = mults_by_k[k]

        def run(key):
            if solver_kind == "sparse":
                return replay_on_device_sparse(state, sgraph, loc, m, key, cfg)
            return replay_on_device(state, graph, ii, jj, m, key, cfg)

        _, objs, befores = run(jax.random.PRNGKey(5))
        float(objs[-1])  # warm
        best, tracking = float("inf"), None
        for rep in range(3):
            t0 = time.perf_counter()
            _, objs, befores = run(jax.random.PRNGKey(6 + rep))
            float(objs[-1])
            best = min(best, time.perf_counter() - t0)
            import numpy as np

            tracking = float(
                (1.0 - (np.asarray(objs) / np.maximum(np.asarray(befores), 1e-9)))
                .mean()
            )
        return best, tracking

    k1, k2 = 3, 10
    t1, _ = timed(k1)
    t2, tracking = timed(k2)
    step_ms = (t2 - t1) / (k2 - k1) * 1e3
    return {
        "metric": f"trace_step_ms_{scenario}",
        "value": round(step_ms, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / step_ms, 3),
        "extra": {
            "scenario": scenario,
            "solver": solver_kind,
            "sweeps": sweeps,
            "steps_timed": (k1, k2),
            "tracking_gain_frac": round(tracking, 4),
            "devices": [str(d) for d in jax.devices()],
        },
    }


def bench_fleet(
    reps: int,
    baseline_ms: float,
    tenants: int,
    n_services: int = 2000,
    n_nodes: int = 256,
    sweeps: int = 9,
) -> dict:
    """Fleet mode: amortized per-tenant decision cost of ONE batched
    device program over N same-shaped tenants vs N sequential solo
    dispatches of the identical kernel (bit-exact decisions — the fleet
    parity tests pin it). The win is the per-solve FIXED cost + dispatch
    overhead RESULTS.md round 5 measured as dominant: the batch pays it
    once per round for the whole fleet. Steady state must run from ONE
    trace of the batched kernel (`jax_traces_total{fn="fleet_solve"}` —
    reported in extra and asserted by the fleet test suite)."""
    import numpy as np

    from kubernetes_rescheduling_tpu.bench.harness import make_fleet_problem
    from kubernetes_rescheduling_tpu.policies import POLICY_IDS
    from kubernetes_rescheduling_tpu.solver.fleet import (
        fleet_solve,
        stack_tenants,
    )
    from kubernetes_rescheduling_tpu.solver.round_loop import decide
    from kubernetes_rescheduling_tpu.telemetry import get_registry

    states, graphs = make_fleet_problem(
        tenants=tenants, n_services=n_services, n_nodes=n_nodes
    )
    st, gr = stack_tenants(states), stack_tenants(graphs)
    pid = jnp.asarray(POLICY_IDS["communication"])
    thr = jnp.asarray(30.0)
    mask = jnp.ones((tenants,), bool)
    rtt_ms = measure_rtt_ms()

    def round_keys(i):
        return jnp.stack(
            [
                jax.random.fold_in(jax.random.PRNGKey(i), t)
                for t in range(tenants)
            ]
        )

    solo = jax.jit(decide)

    # warm both kernels (compile outside the timed reps)
    jax.block_until_ready(fleet_solve(st, gr, pid, thr, round_keys(0), mask))
    jax.block_until_ready(solo(states[0], graphs[0], pid, thr, round_keys(0)[0]))

    fleet_times, solo_times = [], []
    for i in range(reps):
        keys = round_keys(i + 1)
        t0 = time.perf_counter()
        jax.block_until_ready(fleet_solve(st, gr, pid, thr, keys, mask))
        fleet_times.append(time.perf_counter() - t0)
        # the sequential loop a non-fleet service runs: one dispatch per
        # tenant, FENCED per tenant — the solo controller must host-read
        # each tenant's decision to apply its move before the next
        # tenant's round (exactly run_controller's block_until_ready per
        # decide), so every tenant pays the full dispatch + round-trip
        # fixed cost the batch pays once
        t0 = time.perf_counter()
        for t in range(tenants):
            jax.block_until_ready(
                solo(states[t], graphs[t], pid, thr, keys[t])
            )
        solo_times.append(time.perf_counter() - t0)

    fleet_ms = sorted(fleet_times)[len(fleet_times) // 2] * 1e3
    solo_ms = sorted(solo_times)[len(solo_times) // 2] * 1e3
    per_tenant_ms = fleet_ms / tenants
    solo_per_tenant_ms = solo_ms / tenants
    traces = int(
        get_registry()
        .counter("jax_traces_total", labelnames=("fn",))
        .labels(fn="fleet_solve")
        .value
    )

    # rollup overhead: the same steady-state fleet round (solve + the
    # round-closing metrics bundle) with device-side tenant rollups ON
    # vs OFF — what the bounded observability plane costs the loop
    from kubernetes_rescheduling_tpu.solver.fleet import fleet_metrics
    from kubernetes_rescheduling_tpu.telemetry.fleet_rollup import (
        dispatch_fleet_bundle,
    )

    last_pair = jnp.zeros((tenants, 2), jnp.float32)
    flags = jnp.zeros((tenants, 3), jnp.float32)
    act = jnp.ones((tenants,), bool)
    rollup_k = 3
    np.asarray(fleet_metrics(st, gr))  # compile both closers
    np.asarray(
        dispatch_fleet_bundle(st, gr, last_pair, flags, act, top_k=rollup_k)
    )

    def rounds_per_sec(with_rollup: bool) -> float:
        times = []
        for i in range(reps):
            keys = round_keys(100 + i)
            t0 = time.perf_counter()
            jax.block_until_ready(
                fleet_solve(st, gr, pid, thr, keys, mask)
            )
            if with_rollup:
                np.asarray(
                    dispatch_fleet_bundle(
                        st, gr, last_pair, flags, act, top_k=rollup_k
                    )
                )
            else:
                np.asarray(fleet_metrics(st, gr))
            times.append(time.perf_counter() - t0)
        return 1.0 / sorted(times)[len(times) // 2]

    rollup_on_rs = rounds_per_sec(True)
    rollup_off_rs = rounds_per_sec(False)

    # fleet v2: the GLOBAL-solve amortization — ONE batched dispatch
    # re-placing every service in every tenant vs N sequential solo
    # solves of the identical kernel (bit-exact decisions, the fleet-v2
    # parity pins). The global solver's per-solve fixed cost is far
    # larger than the greedy kernel's, so this is where RESULTS.md
    # round 5's fixed-cost dominance pays out hardest. Fewer reps than
    # the greedy cell: each rep is 2·T full solves.
    from kubernetes_rescheduling_tpu.solver.fleet_global import (
        fleet_global_solve,
    )
    from kubernetes_rescheduling_tpu.solver.global_solver import (
        GlobalSolverConfig,
        global_assign,
    )

    gcfg = GlobalSolverConfig(sweeps=sweeps, balance_weight=0.5)
    g_reps = max(1, reps // 2)

    def g_keys(i):
        return jnp.stack(
            [
                jax.random.fold_in(jax.random.PRNGKey(1000 + i), t)
                for t in range(tenants)
            ]
        )

    jax.block_until_ready(
        fleet_global_solve(st, gr, g_keys(0), mask, config=gcfg)
    )
    jax.block_until_ready(
        global_assign(states[0], graphs[0], g_keys(0)[0], gcfg)[0].pod_node
    )
    g_fleet_times, g_solo_times = [], []
    for i in range(g_reps):
        keys = g_keys(i + 1)
        t0 = time.perf_counter()
        jax.block_until_ready(
            fleet_global_solve(st, gr, keys, mask, config=gcfg)
        )
        g_fleet_times.append(time.perf_counter() - t0)
        # the sequential service: one fenced solo solve per tenant (the
        # solo controller host-reads each placement before the next
        # tenant's round — every tenant pays the full fixed cost)
        t0 = time.perf_counter()
        for t in range(tenants):
            jax.block_until_ready(
                global_assign(states[t], graphs[t], keys[t], gcfg)[0].pod_node
            )
        g_solo_times.append(time.perf_counter() - t0)
    g_fleet_ms = sorted(g_fleet_times)[len(g_fleet_times) // 2] * 1e3
    g_solo_ms = sorted(g_solo_times)[len(g_solo_times) // 2] * 1e3
    g_per_tenant_ms = g_fleet_ms / tenants
    g_solo_per_tenant_ms = g_solo_ms / tenants
    g_traces = int(
        get_registry()
        .counter("jax_traces_total", labelnames=("fn",))
        .labels(fn="fleet_global_solve")
        .value
    )

    return {
        "metric": "device_round_ms_fleet_per_tenant",
        "value": round(per_tenant_ms, 4),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / max(per_tenant_ms, 1e-9), 3),
        "extra": {
            "scenario": "fleet",
            "tenants": tenants,
            "services_per_tenant": n_services,
            "nodes_per_tenant": n_nodes,
            "vs_solo": round(solo_per_tenant_ms / max(per_tenant_ms, 1e-9), 3),
            "solo_round_ms_per_tenant": round(solo_per_tenant_ms, 4),
            "fleet_round_ms": round(fleet_ms, 4),
            "solo_round_ms_sequential": round(solo_ms, 4),
            # the structural claim made explicit: every fenced solo
            # dispatch pays ~rtt_ms of fixed cost that the batch pays
            # once per round for the whole fleet
            "rtt_ms": round(rtt_ms, 3),
            "fleet_solve_traces": traces,
            "rollup_rounds_per_sec": round(rollup_on_rs, 3),
            "rollup_off_rounds_per_sec": round(rollup_off_rs, 3),
            "rollup_overhead_frac": round(
                max(0.0, rollup_off_rs / max(rollup_on_rs, 1e-9) - 1.0), 4
            ),
            "devices": [str(d) for d in jax.devices()],
        },
        # the second ledger series the fleet cell appends (BENCH_LEDGER):
        # steady-state fleet rounds/sec WITH the rollup plane on — a
        # throughput series (better: higher), so a future regression in
        # the rollup kernel shows up as this number falling
        "rollup_reading": {
            "metric": "fleet_rounds_per_sec_rollup",
            "value": round(rollup_on_rs, 3),
            "unit": "rounds/s",
            "better": "higher",
            "extra": {
                "scenario": "fleet",
                "tenants": tenants,
                "rollup_top_k": 3,
                "rollup_off_rounds_per_sec": round(rollup_off_rs, 3),
                "devices": [str(d) for d in jax.devices()],
            },
        },
        # fleet v2's headline ledger series (BENCH_LEDGER): amortized
        # per-tenant cost of ONE batched global solve over the fleet —
        # the quality-solver family served as a fleet, with the
        # batched-vs-sequential ratio in extra
        "global_reading": {
            "metric": "fleet_global_round_ms_per_tenant",
            "value": round(g_per_tenant_ms, 4),
            "unit": "ms",
            "extra": {
                "scenario": "fleet",
                "tenants": tenants,
                "services_per_tenant": n_services,
                "nodes_per_tenant": n_nodes,
                "sweeps": sweeps,
                "vs_solo": round(
                    g_solo_per_tenant_ms / max(g_per_tenant_ms, 1e-9), 3
                ),
                "solo_round_ms_per_tenant": round(g_solo_per_tenant_ms, 4),
                "fleet_round_ms": round(g_fleet_ms, 4),
                "solo_round_ms_sequential": round(g_solo_ms, 4),
                "reps": g_reps,
                # one trace for the whole run — the batched solver pays
                # its (large) compile once for the fleet
                "fleet_global_solve_traces": g_traces,
                "devices": [str(d) for d in jax.devices()],
            },
        },
    }


def _sparse_problem(n_services: int, n_nodes: int):
    """Power-law mesh past the dense form's sizing wall — only
    expressible with the block-local sparse storage (50k×2k ≈ 0.4 GB
    sparse vs ≈ 14 GB dense; 100k×4k would need ~56 GB dense)."""
    import numpy as np

    from kubernetes_rescheduling_tpu.core import sparsegraph
    from kubernetes_rescheduling_tpu.core.topology import (
        _random_workmodel,
        state_from_workmodel,
    )

    rng = np.random.default_rng(0)
    wm = _random_workmodel(n_services, rng, powerlaw=True, mean_degree=4.0)
    graph = sparsegraph.from_workmodel(wm)
    state = state_from_workmodel(
        wm,
        node_names=[f"w{i:05d}" for i in range(n_nodes)],
        node_cpu_cap_m=5_000.0,
        seed=0,
    )
    return state, graph


def _sparse50k_problem():
    return _sparse_problem(50_000, 2_000)


def bench_pipeline(baseline_ms: float, rounds: int) -> dict:
    """Pipelined control loop: the SAME live greedy loop run twice on
    identically-seeded 10k-pod × 1k-node clusters — sequential schedule
    vs the software-pipelined one (``[controller] pipeline``). The
    headline is the pipelined wall-clock ms/round; the structural claims
    ride in ``extra``: decisions bit-identical (service/target streams
    compared), wall ≤ target vs the device ms/round, the explicit RTT
    attribution, and the measured overlap ratio. Appends to the perf
    ledger as the ``wall_round_ms`` series (BENCH_LEDGER).

    NOTE on CPU smoke runs: the overlap win is RTT hiding, and rtt_ms
    on a local CPU backend is ~0.1 ms while the sim monitor is
    GIL-bound Python the background thread cannot overlap with host
    work — expect speedup_vs_sequential ≈ 1 ± ambient noise there. The
    single-bundle round-end transfer (the other half of this arc)
    benefits BOTH schedules and is already in the sequential baseline.
    The ≤ 2× wall-vs-device acceptance is the tunneled-rig (BENCH)
    reading."""
    import jax

    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.config import (
        ControllerConfig,
        RescheduleConfig,
    )

    rtt_ms = measure_rtt_ms()

    def run(pipeline: bool):
        backend = make_backend("large", seed=0)
        backend.inject_imbalance(backend.node_names[0])
        cfg = RescheduleConfig(
            algorithm="communication",
            max_rounds=rounds,
            sleep_after_action_s=0.0,
            seed=0,
            controller=ControllerConfig(pipeline=pipeline),
        )
        t0 = time.perf_counter()
        result = run_controller(backend, cfg, key=jax.random.PRNGKey(0))
        return result, time.perf_counter() - t0

    seq, seq_wall = run(False)
    pl, pl_wall = run(True)

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else 0.0

    # drop round 1 (compile) from the medians, like the other live cells
    seq_wall_ms = med([r.wall_s * 1e3 for r in seq.rounds[1:]])
    pl_wall_ms = med([r.wall_s * 1e3 for r in pl.rounds[1:]])
    device_ms = med([r.decision_latency_s * 1e3 for r in seq.rounds[1:]])
    ratios = [
        r.pipeline["overlap_ratio"] for r in pl.rounds if r.pipeline
    ]
    bit_identical = [
        (r.services_moved, r.target, round(r.communication_cost, 6))
        for r in seq.rounds
    ] == [
        (r.services_moved, r.target, round(r.communication_cost, 6))
        for r in pl.rounds
    ]
    return {
        "metric": "wall_round_ms",
        "value": round(pl_wall_ms, 4),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / max(pl_wall_ms, 1e-9), 3),
        "extra": {
            "scenario": "pipeline",
            "rounds": rounds,
            "sequential_wall_round_ms": round(seq_wall_ms, 4),
            "device_ms_per_round": round(device_ms, 4),
            # the acceptance gate: pipelined wall-clock round vs device
            # compute (target <= 2x on the tunneled rig)
            "wall_vs_device": round(pl_wall_ms / max(device_ms, 1e-9), 3),
            "speedup_vs_sequential": round(
                seq_wall_ms / max(pl_wall_ms, 1e-9), 3
            ),
            "rtt_ms": round(rtt_ms, 3),
            "overlap_ratio_mean": round(
                sum(ratios) / len(ratios), 4
            ) if ratios else 0.0,
            "pipelined_rounds": len(ratios),
            "bit_identical": bit_identical,
            "total_wall_s": {
                "sequential": round(seq_wall, 3),
                "pipelined": round(pl_wall, 3),
            },
            "devices": [str(d.platform) for d in jax.devices()],
        },
    }


def bench_scan(baseline_ms: float, rounds: int, block: int) -> dict:
    """Device-resident round scan: the SAME live greedy loop run four
    ways on identically-seeded 2k-svc × 200-node powerlaw clusters —
    sequential, software-pipelined (the PR 9 schedule the scan must
    beat), scanned (``[controller] scan_block``: K rounds fused into
    one ``lax.scan`` dispatch + ONE counted ``round_end`` transfer per
    block, moves replayed afterwards) with the in-block tripwire plane
    armed (the default), and scanned with tripwires compiled out. The
    headline is the armed scanned loop's throughput in rounds/sec
    (``better: higher`` — the first throughput series in the ledger);
    the structural claims ride in ``extra``: records bit-identical
    across all four schedules, ``jax_traces_total{scan_rounds}`` pinned
    at one compile per tripwire variant, exactly one ``round_end``
    transfer per block, the tripwire plane's throughput overhead
    (``tripwire_overhead_frac``), and the speedups vs both per-round
    schedules (the CPU-sim acceptance gate is ≥5× vs pipelined here;
    the 10× target is the on-rig BENCH_r06 number, where each avoided
    round trip also buys back a ~100 ms tunnel RTT).

    Each schedule runs once for warm-up (compiles) and once timed on a
    fresh identically-seeded backend, so the throughput reading is the
    steady state, not the compile."""
    import jax

    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.config import (
        ControllerConfig,
        ObsConfig,
        RescheduleConfig,
    )
    from kubernetes_rescheduling_tpu.telemetry import get_registry

    def run(mode: str, n_rounds: int):
        backend = make_backend("powerlaw", seed=0)
        backend.inject_imbalance(backend.node_names[0])
        cfg = RescheduleConfig(
            algorithm="communication",
            max_rounds=n_rounds,
            sleep_after_action_s=0.0,
            seed=0,
            controller=ControllerConfig(
                pipeline=mode == "pipelined",
                scan_block=block if mode.startswith("scanned") else 0,
            ),
            obs=ObsConfig(scan_tripwires=mode != "scanned_off"),
        )
        t0 = time.perf_counter()
        result = run_controller(backend, cfg, key=jax.random.PRNGKey(0))
        return result, time.perf_counter() - t0

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else 0.0

    # shape the round count so EVERY timed round of the scanned run is a
    # scanned round: at least two full blocks and no tail (tail rounds
    # drain to the sequential path — the steady-state median below must
    # never average the wrong schedule, and an all-tail run would even
    # report the sequential rate under the scanned label)
    block = max(1, block)
    rounds = max(rounds, 2 * block)
    rounds -= rounds % block

    rates = {}
    wall_rates = {}
    results = {}
    for mode in ("sequential", "pipelined", "scanned", "scanned_off"):
        run(mode, block)  # warm-up: pay the compiles
        res, wall = run(mode, rounds)
        # steady-state throughput: the median per-round wall with the
        # first block dropped (bench_pipeline's drop-the-compile-round
        # convention), so backend construction and the one-time
        # edge-list build don't read as per-round cost; the raw
        # whole-loop rate rides in extra
        steady = med([r.wall_s for r in res.rounds[block:]])
        rates[mode] = 1.0 / steady if steady > 0 else 0.0
        wall_rates[mode] = len(res.rounds) / wall if wall > 0 else 0.0
        results[mode] = res

    def stream(res):
        return [
            (r.services_moved, r.target, round(r.communication_cost, 6))
            for r in res.rounds
        ]

    bit_identical = (
        stream(results["sequential"])
        == stream(results["pipelined"])
        == stream(results["scanned"])
        == stream(results["scanned_off"])
    )
    reg = get_registry()
    scan_traces = int(
        reg.counter("jax_traces_total", labelnames=("fn",))
        .labels(fn="scan_rounds")
        .value
    )
    blocks = int(reg.counter("scan_blocks_total").value)
    value = rates["scanned"]
    baseline_rps = 1e3 / baseline_ms  # the BASELINE.md ms/round target
    return {
        "metric": "scan_rounds_per_sec",
        "value": round(value, 3),
        "unit": "rounds/s",
        "better": "higher",
        "vs_baseline": round(value / baseline_rps, 3),
        "extra": {
            "scenario": "scan",
            "rounds": rounds,
            "scan_block": block,
            "scan_blocks_total": blocks,
            "sequential_rounds_per_sec": round(rates["sequential"], 3),
            "pipelined_rounds_per_sec": round(rates["pipelined"], 3),
            "whole_loop_rounds_per_sec": {
                m: round(v, 3) for m, v in wall_rates.items()
            },
            # the acceptance gate: scanned throughput vs the pipelined
            # loop (target >= 5x on CPU sim at powerlaw 2k x 200)
            "speedup_vs_pipelined": round(
                value / max(rates["pipelined"], 1e-9), 3
            ),
            "speedup_vs_sequential": round(
                value / max(rates["sequential"], 1e-9), 3
            ),
            "bit_identical": bit_identical,
            # the tripwire plane's cost: the same scanned loop with the
            # in-block tripwires compiled out (ObsConfig.scan_tripwires
            # False restores the pre-tripwire program byte-for-byte);
            # overhead_frac is the throughput the armed plane gives up
            "scanned_tripwire_off_rounds_per_sec": round(
                rates["scanned_off"], 3
            ),
            "tripwire_overhead_frac": round(
                1.0 - rates["scanned"] / max(rates["scanned_off"], 1e-9),
                4,
            ),
            # 1 steady-state compile of the fused kernel PER tripwire
            # variant across warm-up + timed runs (same shapes — a
            # retrace would be the old per-round dispatch cost wearing
            # a scan costume); tripwire on/off is a static flag, so the
            # two schedules legitimately compile once each
            "scan_traces": scan_traces,
            "traces_pinned": scan_traces == 2,
            "devices": [str(d.platform) for d in jax.devices()],
        },
    }


def bench_elastic(baseline_ms: float, rounds: int) -> dict:
    """Elastic topologies: the full controller loop under sustained
    seeded churn (diurnal-autoscale: every service's replica target
    swings ×0.5–×2 with its request-rate series, one node drain/add
    cycle mid-run). The reading is the steady-state median device
    ms/round of the greedy decision kernel; the structural claim is in
    ``extra``: churn applied every round, yet the kernel compiled
    exactly ``1 + bucket_promotions`` times — shape buckets + the
    name-stripped device views absorb everything else."""
    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.config import (
        ElasticConfig,
        RescheduleConfig,
    )
    from kubernetes_rescheduling_tpu.telemetry import get_registry

    backend = make_backend("powerlaw", seed=0)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm="communication",
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=0,
        elastic=ElasticConfig(profile="diurnal-autoscale", seed=0),
    )
    t0 = time.perf_counter()
    result = run_controller(backend, cfg, key=jax.random.PRNGKey(0))
    wall_s = time.perf_counter() - t0
    lat_ms = sorted(r.decision_latency_s * 1e3 for r in result.rounds[1:])
    device_ms = lat_ms[len(lat_ms) // 2] if lat_ms else 0.0
    churned = [r for r in result.rounds if r.churn]
    events = sum(len(r.churn["events"]) for r in churned)
    promotions = max((r.churn["promotions"] for r in churned), default=0)
    traces = int(
        get_registry()
        .counter("jax_traces_total", labelnames=("fn",))
        .labels(fn="controller_decide")
        .value
    )
    return {
        "metric": "device_round_ms_elastic",
        "value": round(device_ms, 4),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / max(device_ms, 1e-9), 3),
        "extra": {
            "scenario": "elastic",
            "profile": "diurnal-autoscale",
            "rounds": rounds,
            "records": len(result.rounds),
            "skipped_rounds": result.skipped_rounds,
            "churn_events": events,
            "bucket_promotions": promotions,
            "decide_traces": traces,
            # the invariant the elastic test suite pins: one steady-state
            # compile plus AT MOST one per counted bucket promotion (a
            # promotion landing before the first decide folds into the
            # first compile — no separate retrace)
            "traces_pinned": traces <= 1 + promotions,
            "final_live": backend.live_counts(),
            "wall_s": round(wall_s, 3),
            "devices": [str(d.platform) for d in jax.devices()],
        },
    }


def bench_forecast(baseline_ms: float, rounds: int) -> dict:
    """Forecast plane: the full proactive controller loop under
    sustained seeded diurnal churn — the online per-node ridge
    forecaster folds each round's observed loads into its normal
    equations, re-solves, and the decision kernel scores reactive CAR's
    policy against the PREDICTED next-window state. The reading is the
    steady-state median device ms/round (forecast update + proactive
    decide, the whole per-round device budget); the structural claims
    ride in ``extra``: the forecaster's skill vs the persistence
    baseline, and both proactive kernels compiled exactly
    ``1 + bucket_promotions`` times."""
    import dataclasses

    from kubernetes_rescheduling_tpu.bench.controller import run_controller
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.config import (
        ElasticConfig,
        RescheduleConfig,
    )
    from kubernetes_rescheduling_tpu.telemetry import get_registry

    backend = make_backend("powerlaw", seed=0)
    # metrics-reading noise: the regime where the differenced model's
    # mean-reversion edge over persistence is provable (see
    # bench/harness.run_forecast_headtohead)
    backend.load = dataclasses.replace(backend.load, noise_frac=0.05)
    backend.inject_imbalance(backend.node_names[0])
    cfg = RescheduleConfig(
        algorithm="proactive",
        max_rounds=rounds,
        sleep_after_action_s=0.0,
        seed=0,
        elastic=ElasticConfig(profile="diurnal-autoscale", seed=0),
    )
    t0 = time.perf_counter()
    result = run_controller(backend, cfg, key=jax.random.PRNGKey(0))
    wall_s = time.perf_counter() - t0
    lat_ms = sorted(r.decision_latency_s * 1e3 for r in result.rounds[1:])
    device_ms = lat_ms[len(lat_ms) // 2] if lat_ms else 0.0
    churned = [r for r in result.rounds if r.churn]
    promotions = max((r.churn["promotions"] for r in churned), default=0)
    forecast = next(
        (r.forecast for r in reversed(result.rounds) if r.forecast), {}
    )
    trained_skills = [
        r.forecast["skill"]
        for r in result.rounds
        if r.forecast and r.forecast["trained"]
    ]
    tail = trained_skills[-10:]
    skill_tail = sum(tail) / len(tail) if tail else 0.0

    def traces(fn):
        return int(
            get_registry()
            .counter("jax_traces_total", labelnames=("fn",))
            .labels(fn=fn)
            .value
        )

    fc_traces = traces("controller_forecast")
    dec_traces = traces("controller_decide_proactive")
    return {
        "metric": "device_round_ms_forecast",
        "value": round(device_ms, 4),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / max(device_ms, 1e-9), 3),
        "extra": {
            "scenario": "forecast",
            "profile": "diurnal-autoscale",
            "rounds": rounds,
            "records": len(result.rounds),
            "skipped_rounds": result.skipped_rounds,
            "bucket_promotions": promotions,
            "forecast_traces": fc_traces,
            "decide_traces": dec_traces,
            # the proactive invariant the forecast test suite pins: one
            # steady-state compile per kernel plus at most one per
            # counted bucket promotion
            "traces_pinned": (
                fc_traces <= 1 + promotions and dec_traces <= 1 + promotions
            ),
            "forecast_skill": round(float(forecast.get("skill", 0.0)), 4),
            # final-round skill rides the diurnal cycle's phase; the
            # tail mean is the steadier reading
            "forecast_skill_tail_mean": round(float(skill_tail), 4),
            "forecast_mae": round(float(forecast.get("mae_model", 0.0)), 4),
            "forecast_mae_persistence": round(
                float(forecast.get("mae_persistence", 0.0)), 4
            ),
            "forecast_mode": forecast.get("mode", "cold"),
            "wall_s": round(wall_s, 3),
            "devices": [str(d.platform) for d in jax.devices()],
        },
    }


def bench_serve(requests: int, rate_rps: int, max_batch: int) -> dict:
    """The serving plane: open-loop arrivals through the bounded batcher,
    ONE vmapped decide dispatch per coalesced batch — the repo's first
    request-grain perf pair (placements/sec + p99 ms).

    The headline is achieved placements/sec over the soak's wall clock;
    ``vs_baseline`` is achieved/offered, so 1.0 means the plane kept up
    with the open-loop arrival rate and anything below it means requests
    queued faster than they were answered. The p99 request latency is a
    NESTED ledger series (``p99_reading``, better: lower) — the schema
    checker enforces the pairing, because a rate that trends up while
    the tail trends away is a regression wearing a throughput costume.
    """
    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.bench.loadgen import open_loop_arrivals
    from kubernetes_rescheduling_tpu.bench.serve import run_serve_soak
    from kubernetes_rescheduling_tpu.config import ServingConfig
    from kubernetes_rescheduling_tpu.serving import ServingEngine
    from kubernetes_rescheduling_tpu.serving.kernel import place_batch

    backend = make_backend("dense", 0)
    engine = ServingEngine(
        backend,
        config=ServingConfig(
            max_batch=max_batch,
            # the perf cell measures throughput and tails, not overload
            # policy: queue deep enough to hold the whole soak, no
            # deadline — the overload soaks live in tests/test_serving.py
            queue_depth=max(requests, 64),
            deadline_ms=0.0,
        ),
    )
    services = list(engine.graph.names)
    traces0 = place_batch.traces()
    with engine:
        engine.place(services[0])  # compile outside the timed soak
        warm_traces = place_batch.traces() - traces0
        soak = run_serve_soak(
            engine,
            services,
            open_loop_arrivals(rate_rps, requests, seed=0),
        )
        steady_traces = place_batch.traces() - traces0 - warm_traces
    value = soak["placements_per_sec"]
    p99 = soak["p99_ms"]
    extra_common = {
        "scenario": "serve",
        "requests": requests,
        "offered_rps": rate_rps,
        "max_batch": max_batch,
        "devices": [str(d.platform) for d in jax.devices()],
    }
    return {
        "metric": "serving_placements_per_sec",
        "value": round(value, 3),
        "unit": "req/s",
        "better": "higher",
        # achieved/offered: 1.0 = the plane kept up with the arrival rate
        "vs_baseline": round(value / max(float(rate_rps), 1e-9), 3),
        "extra": {
            **extra_common,
            "outcomes": soak["outcomes"],
            "shed_reasons": soak["shed_reasons"],
            "accounting_exact": (
                soak["answered"] + soak["shed"] + soak["timed_out"]
                == soak["submitted"]
            ),
            "p50_ms": round(soak["p50_ms"], 3),
            "p95_ms": round(soak["p95_ms"], 3),
            "dispatches": engine.dispatches,
            "batch_sizes": {
                str(k): v for k, v in sorted(engine._batch_sizes.items())
            },
            # padded static batch shape: the soak re-traces NOTHING after
            # the warmup dispatch (the 1-steady-state-trace invariant)
            "steady_state_traces": steady_traces,
            "traces_pinned": steady_traces == 0,
            "wall_s": round(soak["wall_s"], 3),
        },
        "p99_reading": {
            "metric": "serving_p99_ms",
            "value": round(p99, 3),
            "unit": "ms",
            "better": "lower",
            # vs the [serving] block's default per-request deadline:
            # >1 means the tail clears it with room
            "vs_baseline": round(250.0 / max(p99, 1e-9), 3),
            "extra": extra_common,
        },
        "slo_reading": _slo_reading(soak, extra_common),
    }


def _slo_reading(soak: dict, extra_common: dict) -> dict:
    """The serve cell's third ledger series: the fraction of a 99%
    availability SLO's error budget this soak burned (answered = good,
    shed/timeout = bad; 1.0 = budget exactly spent, >1 = SLO violated)."""
    from kubernetes_rescheduling_tpu.telemetry.slo import budget_burn_frac

    objective = 0.99
    good = soak["answered"]
    bad = soak["shed"] + soak["timed_out"]
    burn = budget_burn_frac(good, bad, objective)
    return {
        "metric": "slo_budget_burn_frac",
        "value": round(min(burn, 1e9), 4),
        "unit": "frac",
        "better": "lower",
        # vs a full budget: the headroom multiple (capped; 0 burn means
        # the whole budget is headroom)
        "vs_baseline": round(1.0 / max(burn, 1e-9), 3) if burn > 0 else 1e9,
        "extra": {
            **extra_common,
            "objective": objective,
            "good": good,
            "bad": bad,
        },
    }


def main() -> int:
    scenario = os.environ.get("BENCH_SCENARIO", "large")
    sweeps = _env_int("BENCH_SWEEPS", 9)
    reps = _env_int("BENCH_REPS", 5)
    restarts = _env_int("BENCH_RESTARTS", 1)
    solver_kind = os.environ.get("BENCH_SOLVER", "dense")

    baseline_ms = 100.0  # BASELINE.md: <100 ms/round at 10k x 1k

    if scenario == "multichip":
        # force the dp mesh BEFORE any jax device use: on a dev box this
        # virtualizes BENCH_DEVICES host-CPU devices (the tier-1 shape);
        # on a slice with enough real chips it is a no-op, so the same
        # cell measures real hardware unchanged
        import __graft_entry__ as graft

        graft._force_virtual_devices(_env_int("BENCH_DEVICES", 8))
        from kubernetes_rescheduling_tpu.bench.multichip import (
            bench_multichip,
        )

        result = bench_multichip(
            tenants=_env_int("BENCH_TENANTS", 16),
            n_services=_env_int("BENCH_FLEET_SERVICES", 2000),
            n_nodes=_env_int("BENCH_FLEET_NODES", 256),
            rounds=_env_int("BENCH_SCAN_BLOCK", 8),
            reps=reps,
            rtt_ms=measure_rtt_ms(),
        )
        _ledger_append(result)
        # the per-device step rollup is its own ledger series (better:
        # lower) — a device-imbalance regression trends independently
        if isinstance(result.get("device_step_reading"), dict):
            _ledger_append(result["device_step_reading"])
        _write_multichip_record(result)
        print(json.dumps(result))
        return 0

    if scenario == "fleet":
        result = bench_fleet(
            reps,
            baseline_ms,
            _env_int("BENCH_TENANTS", 16),
            n_services=_env_int("BENCH_FLEET_SERVICES", 2000),
            n_nodes=_env_int("BENCH_FLEET_NODES", 256),
            sweeps=sweeps,
        )
        _ledger_append(result)
        # the rollup-overhead reading is its own ledger series (a
        # throughput metric, better: higher), and so is the fleet-v2
        # batched global solve's amortized per-tenant cost
        if isinstance(result.get("rollup_reading"), dict):
            _ledger_append(result["rollup_reading"])
        if isinstance(result.get("global_reading"), dict):
            _ledger_append(result["global_reading"])
        print(json.dumps(result))
        return 0

    if scenario == "pipeline":
        result = bench_pipeline(baseline_ms, _env_int("BENCH_ROUNDS", 12))
        _ledger_append(result)
        print(json.dumps(result))
        return 0

    if scenario == "scan":
        result = bench_scan(
            baseline_ms,
            _env_int("BENCH_ROUNDS", 48),
            _env_int("BENCH_SCAN_BLOCK", 16),
        )
        _ledger_append(result)
        print(json.dumps(result))
        return 0

    if scenario == "elastic":
        result = bench_elastic(baseline_ms, _env_int("BENCH_ROUNDS", 30))
        _ledger_append(result)
        print(json.dumps(result))
        return 0

    if scenario == "forecast":
        result = bench_forecast(baseline_ms, _env_int("BENCH_ROUNDS", 30))
        _ledger_append(result)
        print(json.dumps(result))
        return 0

    if scenario == "serve":
        result = bench_serve(
            _env_int("BENCH_SERVE_REQUESTS", 256),
            _env_int("BENCH_SERVE_RPS", 200),
            _env_int("BENCH_SERVE_BATCH", 8),
        )
        _ledger_append(result)
        # the p99 latency and the SLO budget burn are their own ledger
        # series, paired with the throughput headline (the schema
        # checker enforces both nestings)
        if isinstance(result.get("p99_reading"), dict):
            _ledger_append(result["p99_reading"])
        if isinstance(result.get("slo_reading"), dict):
            _ledger_append(result["slo_reading"])
        print(json.dumps(result))
        return 0

    if scenario in ("trace", "trace50k"):
        result = bench_trace(sweeps, baseline_ms, scenario, solver_kind)
        _ledger_append(result)
        print(json.dumps(result))
        return 0

    from kubernetes_rescheduling_tpu.objectives import communication_cost
    from kubernetes_rescheduling_tpu.solver import (
        GlobalSolverConfig,
        global_assign,
        global_assign_sparse,
        sparse_pod_comm_cost,
    )

    cfg = GlobalSolverConfig(sweeps=sweeps)

    if scenario == "sparse50k":
        solver_kind = "sparse"
        state, graph = _sparse50k_problem()
    elif scenario == "sparse100k":
        solver_kind = "sparse"
        state, graph = _sparse_problem(100_000, 4_000)
    else:
        from kubernetes_rescheduling_tpu.bench.harness import make_backend

        backend = make_backend(scenario, seed=0)
        state = backend.monitor()
        graph = backend.comm_graph()
        if solver_kind == "sparse":
            from kubernetes_rescheduling_tpu.core import sparsegraph

            graph = sparsegraph.from_comm_graph(graph)

    if solver_kind == "sparse":
        solve = global_assign_sparse
        cost_of = sparse_pod_comm_cost
    else:
        solve = global_assign
        cost_of = communication_cost

    key = jax.random.PRNGKey(0)
    rtt_ms = measure_rtt_ms()

    # prebuilt pair weights (dense): the controller-realistic loops reuse
    # the W matrix across rounds with an unchanged service set — measured
    # ~4 ms/round at 10k×1k. Always passed as an ARGUMENT (a closure would
    # bake 200 MB into the HLO as a constant).
    w_prep = None
    if solver_kind == "dense":
        from kubernetes_rescheduling_tpu.solver.global_solver import (
            prepare_weights,
        )

        w_prep = prepare_weights(state, graph, cfg)

        def round_once(st, g, w, k):
            return solve(st, g, k, cfg, w_mm=w)

    else:

        def round_once(st, g, w, k):
            return solve(st, g, k, cfg)

    # warm-up: compile + first run — through round_once, the exact
    # signature the pipelined loop times (the w_mm variant is a distinct
    # trace; warming a different signature would hide a compile in the
    # first timed round). Force a scalar host read — on tunneled PJRT
    # backends block_until_ready can return before remote execution
    # completes, so a device->host scalar is the only honest fence.
    new_state, info = round_once(state, graph, w_prep, key)
    float(info["objective_after"])

    # single-round fenced latency with DEVICE-RESIDENT controller state:
    # each round's solve consumes the previous round's placement (donated
    # buffers — no state copy), and the only per-round host traffic is the
    # key upload and one scalar read. fenced ≈ rtt + dispatch + device;
    # rtt_ms above makes the tunnel's share explicit (off-tunnel, expect
    # fenced ≈ device + ~1-2 ms dispatch).
    from kubernetes_rescheduling_tpu.utils.profiling import trace_to

    round_fn = jax.jit(round_once, donate_argnums=(0,))
    # donate a COPY: the original state arrays are reused by the pipelined
    # and slope measurements below, and a donated buffer is invalidated.
    # Warm round_fn itself — it is a distinct jit wrapper from the warm-up
    # call above and would otherwise compile inside the first timed round.
    st = jax.tree_util.tree_map(jnp.array, state)
    st, inf = round_fn(st, graph, w_prep, jax.random.PRNGKey(99))
    float(inf["objective_after"])
    times = []
    with trace_to(os.environ.get("BENCH_TRACE_DIR")):
        for i in range(reps):
            k = jax.random.PRNGKey(i + 1)
            t0 = time.perf_counter()
            st, inf = round_fn(st, graph, w_prep, k)
            float(inf["objective_after"])  # host read = completion fence
            times.append(time.perf_counter() - t0)
    single_ms = sorted(times)[len(times) // 2]  # median
    single_ms *= 1e3

    # steady-state per-round latency: the online control loop — only the
    # final round is fenced; per-round cost amortizes the host round trip.
    # Reuses the prepared weights, as the production controller can.
    # Min-of-3 passes: on the tunneled rig a single pass swings ±10 ms with
    # tunnel contention, and contention only ever adds time.
    rounds = 10
    solve_ms = float("inf")
    for p in range(3):
        st = state
        t0 = time.perf_counter()
        last_inf = None
        for i in range(rounds):
            st, last_inf = round_once(
                st, graph, w_prep, jax.random.PRNGKey(100 + p * rounds + i)
            )
        float(last_inf["objective_after"])
        solve_ms = min(solve_ms, (time.perf_counter() - t0) / rounds * 1e3)

    # device-only per-round latency (slope method)
    @partial(jax.jit, static_argnames=("k",))
    def chained(st0, g, key0, k):
        # g must be an argument, not a closure: closed-over arrays become
        # HLO constants, and a 10k x 10k adjacency embedded in the program
        # overflows remote-compile request limits
        def body(st_c, i):
            st_n, inf_n = solve(st_c, g, jax.random.fold_in(key0, i), cfg)
            return st_n, inf_n["objective_after"]

        return jax.lax.scan(body, st0, jnp.arange(k))

    device_ms = slope_device_ms(chained, state, graph)

    # device slope with the prepared weights (the controller-realistic
    # per-round device cost; the self-built number above stays for
    # continuity with earlier rounds' measurements)
    device_prep_ms = None
    if w_prep is not None:

        @partial(jax.jit, static_argnames=("k",))
        def chained_prep(st0, g, w, key0, k):
            def body(st_c, i):
                st_n, inf_n = solve(
                    st_c, g, jax.random.fold_in(key0, i), cfg, w_mm=w
                )
                return st_n, inf_n["objective_after"]

            return jax.lax.scan(body, st0, jnp.arange(k))

        device_prep_ms = slope_device_ms(
            lambda s, g, k0, k: chained_prep(s, g, w_prep, k0, k),
            state,
            graph,
        )

    # optional best-of-N over the device mesh (parallel.solve_with_restarts):
    # on one chip the restarts run sequentially; on a slice they shard over
    # dp. Both solvers route through the one production entry.
    restart_extra = {"restarts": max(restarts, 1)}
    if restarts > 1:
        from kubernetes_rescheduling_tpu.parallel import solve_with_restarts

        multi_state, multi_info = solve_with_restarts(
            state,
            graph if solver_kind == "dense" else None,
            jax.random.PRNGKey(0),
            n_restarts=restarts,
            config=cfg,
            sparse_graph=graph if solver_kind == "sparse" else None,
        )
        restart_extra["multi_restart_cost_after"] = float(
            cost_of(multi_state, graph)
        )
        restart_extra["restart_objectives"] = [
            round(float(o), 2) for o in multi_info["restart_objectives"]
        ]

    cost_before = float(cost_of(state, graph))
    cost_after = float(cost_of(new_state, graph))
    num_services = (
        graph.num_services
        if hasattr(graph, "num_services")
        else len(graph.names)
    )
    # HEADLINE = the measurement this benchmark itself calls "the stable
    # reading": the device slope (prepared weights where the controller
    # can reuse them). The pipelined and fenced numbers ride the tunnel
    # (±10 ms swings measured round to round) and live in extra with the
    # RTT attribution — comparable run-to-run without the variance
    # footnote.
    headline_ms = device_prep_ms if device_prep_ms is not None else device_ms
    result = {
                "metric": f"device_round_ms_{scenario}",
                "value": round(headline_ms, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / headline_ms, 3),
                "extra": {
                    "scenario": scenario,
                    "solver": solver_kind,
                    "sweeps": sweeps,
                    "rounds_pipelined": rounds,
                    "pipelined_round_ms": round(solve_ms, 3),
                    "single_round_fenced_ms": round(single_ms, 3),
                    "device_ms_per_round": round(device_ms, 3),
                    **(
                        {"device_ms_prepared": round(device_prep_ms, 3)}
                        if device_prep_ms is not None
                        else {}
                    ),
                    "rtt_ms": round(rtt_ms, 3),
                    "fenced_minus_rtt_ms": round(single_ms - rtt_ms, 3),
                    "vs_baseline_fenced": round(baseline_ms / single_ms, 3),
                    "vs_baseline_pipelined": round(baseline_ms / solve_ms, 3),
                    "devices": [str(d) for d in jax.devices()],
                    "communication_cost_before": cost_before,
                    "communication_cost_after": cost_after,
                    "services_per_sec_equiv": round(
                        num_services / (headline_ms / 1e3), 1
                    ),
                    **restart_extra,
                },
            }
    _ledger_append(result)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
