#!/usr/bin/env python3
"""Headline benchmark — one JSON line for the driver.

Metric: wall-clock latency of one globally-optimal rescheduling round at the
north-star scale (10k pods / 1k nodes, power-law service mesh) on a single
chip — the batched global solve that replaces the reference's
one-deployment-per-round greedy loop (which is paced at 15 s/round,
reference main.py:27,100, and scores O(pods·nodes) in Python,
rescheduling.py:188-195).

Baseline: BASELINE.md's target of <100 ms/round at 10k×1k. ``vs_baseline``
is baseline/value, so >1 means faster than target.

Environment knobs:
  BENCH_SCENARIO  large (default) | powerlaw | dense | mubench
  BENCH_SWEEPS    solver sweeps per round (default 9)
  BENCH_REPS      timed repetitions (default 5)
  BENCH_RESTARTS  best-of-N solves over the device mesh (default 1)
  BENCH_TRACE_DIR write a jax.profiler trace of the timed loop here
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax


def main() -> int:
    scenario = os.environ.get("BENCH_SCENARIO", "large")
    sweeps = int(os.environ.get("BENCH_SWEEPS", "9"))
    reps = int(os.environ.get("BENCH_REPS", "5"))
    restarts = int(os.environ.get("BENCH_RESTARTS", "1"))

    from kubernetes_rescheduling_tpu.bench.harness import make_backend
    from kubernetes_rescheduling_tpu.objectives import communication_cost
    from kubernetes_rescheduling_tpu.solver import GlobalSolverConfig, global_assign

    backend = make_backend(scenario, seed=0)
    state = backend.monitor()
    graph = backend.comm_graph()
    cfg = GlobalSolverConfig(sweeps=sweeps)
    key = jax.random.PRNGKey(0)

    # warm-up: compile + first run. Force a scalar host read — on tunneled
    # PJRT backends block_until_ready can return before remote execution
    # completes, so a device->host scalar is the only honest fence.
    new_state, info = global_assign(state, graph, key, cfg)
    float(info["objective_after"])

    # single-round latency: fence every round (includes one full host<->device
    # round trip per solve — the tunnel RTT floor alone is ~65 ms here)
    from kubernetes_rescheduling_tpu.utils.profiling import trace_to

    times = []
    with trace_to(os.environ.get("BENCH_TRACE_DIR")):
        for i in range(reps):
            k = jax.random.PRNGKey(i + 1)
            t0 = time.perf_counter()
            _, inf = global_assign(state, graph, k, cfg)
            float(inf["objective_after"])  # host read = completion fence
            times.append(time.perf_counter() - t0)
    single_ms = sorted(times)[len(times) // 2] * 1e3  # median

    # steady-state per-round latency: the online control loop — each round's
    # solve consumes the previous round's placement (a true data dependency,
    # so nothing can be elided) and only the final round is fenced. This is
    # how the multi-round controller actually runs (reference main.py loops
    # 10 rounds); per-round cost amortizes the host round trip.
    rounds = 10
    st = state
    t0 = time.perf_counter()
    last_inf = None
    for i in range(rounds):
        st, last_inf = global_assign(st, graph, jax.random.PRNGKey(100 + i), cfg)
    float(last_inf["objective_after"])
    solve_ms = (time.perf_counter() - t0) / rounds * 1e3

    # device-only per-round latency: K chained solves inside ONE jitted
    # program (lax.scan with a true state dependency), fenced once. A single
    # dispatch+fence costs the same regardless of K, so timing K1 and K2
    # and taking the slope isolates pure device compute per round — no
    # tunnel-RTT subtraction, no profiler attribution guesswork.
    import jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnames=("k",))
    def chained(st0, g, key0, k):
        # g must be an argument, not a closure: closed-over arrays become
        # HLO constants, and a 10k x 10k adjacency embedded in the program
        # overflows remote-compile request limits
        def body(st_c, i):
            st_n, inf_n = global_assign(st_c, g, jax.random.fold_in(key0, i), cfg)
            return st_n, inf_n["objective_after"]
        return jax.lax.scan(body, st0, jnp.arange(k))

    def timed_chain(k):
        _, objs = chained(state, graph, jax.random.PRNGKey(7), k)
        float(objs[-1])  # warm-up/compile
        best = float("inf")
        for rep in range(3):  # min-of-3: tunnel contention only ever ADDS time
            t = time.perf_counter()
            _, objs = chained(state, graph, jax.random.PRNGKey(8 + rep), k)
            float(objs[-1])  # completion fence
            best = min(best, time.perf_counter() - t)
        return best

    k1, k2 = 2, 12
    device_ms = (timed_chain(k2) - timed_chain(k1)) / (k2 - k1) * 1e3

    # optional best-of-N over the device mesh (parallel.solve_with_restarts):
    # on one chip the restarts run sequentially; on a slice they shard over dp
    restart_extra = {"restarts": restarts}
    if restarts > 1:
        from kubernetes_rescheduling_tpu.parallel import solve_with_restarts

        multi_state, multi_info = solve_with_restarts(
            state,
            graph,
            jax.random.PRNGKey(0),
            n_restarts=restarts,
            config=cfg,
        )
        restart_extra["multi_restart_cost_after"] = float(
            communication_cost(multi_state, graph)
        )
        restart_extra["restart_objectives"] = [
            round(float(o), 2) for o in multi_info["restart_objectives"]
        ]

    baseline_ms = 100.0  # BASELINE.md: <100 ms/round at 10k x 1k
    cost_before = float(communication_cost(state, graph))
    cost_after = float(communication_cost(new_state, graph))
    print(
        json.dumps(
            {
                "metric": f"global_solve_round_ms_{scenario}",
                "value": round(solve_ms, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / solve_ms, 3),
                "extra": {
                    "scenario": scenario,
                    "sweeps": sweeps,
                    "rounds_pipelined": rounds,
                    "single_round_fenced_ms": round(single_ms, 3),
                    "device_ms_per_round": round(device_ms, 3),
                    "vs_baseline_fenced": round(baseline_ms / single_ms, 3),
                    "vs_baseline_device": round(baseline_ms / device_ms, 3),
                    "devices": [str(d) for d in jax.devices()],
                    "communication_cost_before": cost_before,
                    "communication_cost_after": cost_after,
                    "services_per_sec_equiv": round(
                        graph.num_services / (solve_ms / 1e3), 1
                    ),
                    **restart_extra,
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
